"""Flywheel control-loop tests (fedmse_tpu/flywheel/): the acceptance
contracts pinned —

  * reservoir contents are padding/layout-invariant (absolute-gateway
    keyed priority streams, PARITY.md §8 host edition);
  * with the flywheel disabled (no intake) the continuous front is
    BIT-identical to one that never heard of the flywheel — and an
    attached-but-never-triggering flywheel changes no score/verdict byte;
  * zero dropped/duplicated tickets across a mid-load full-payload swap
    (params + banks + thresholds in ONE call), with per-batch regime
    atomicity;
  * candidate-state scoring equals post-install scoring and leaves the
    resident state untouched;
  * DriftMonitor cooldown hysteresis + last_rebaseline telemetry;
  * the end-to-end loop: train -> serve -> inject shift -> buffer fills
    -> fine-tune fires -> swap lands -> detection recovers while a
    frozen engine degrades.
"""

import dataclasses

import numpy as np
import pytest

import jax

from fedmse_tpu.config import ExperimentConfig
from fedmse_tpu.flywheel import (FlywheelBuffer, FlywheelController,
                                 refit_calibration)
from fedmse_tpu.flywheel.harness import (host_auc, stream_with_polling,
                                         ticket_integrity)
from fedmse_tpu.models import init_stacked_params, make_model
from fedmse_tpu.serving import (ContinuousBatcher, DriftMonitor,
                                ServingEngine, fit_calibration)

pytestmark = pytest.mark.flywheel

DIM = 10
N = 4


def _setup(score_kind="auto", seed=0, max_bucket=64):
    rng = np.random.default_rng(seed)
    model = make_model("autoencoder", DIM)
    params = init_stacked_params(model, jax.random.key(seed), N)
    train_x = rng.normal(size=(N, 80, DIM)).astype(np.float32)
    eng = ServingEngine.from_federation(
        model, "autoencoder", params, train_x=train_x,
        score_kind=score_kind, knn_bank_size=32, max_bucket=max_bucket)
    valid_x = rng.normal(size=(N, 120, DIM)).astype(np.float32)
    cal = fit_calibration(eng, valid_x, percentile=99.0)
    rows = rng.normal(size=(500, DIM)).astype(np.float32)
    gws = rng.integers(0, N, 500).astype(np.int32)
    return model, params, train_x, eng, cal, rows, gws


# ------------------------------ reservoir ------------------------------ #

def test_buffer_padding_and_layout_invariant():
    """Gateway g's retained rows depend only on (seed, g, g's own row
    arrival order): growing the gateway axis and re-interleaving OTHER
    gateways' traffic must not move a byte (PARITY.md §8)."""
    rng = np.random.default_rng(3)
    per_g = {g: rng.normal(size=(60, DIM)).astype(np.float32)
             for g in range(3)}
    a = FlywheelBuffer(3, DIM, capacity=16, seed=5)
    b = FlywheelBuffer(9, DIM, capacity=16, seed=5)  # padded axis
    # a: admit gateway-major; b: admit row-interleaved, wider axis —
    # per-gateway arrival order is identical, everything else differs
    for g in range(3):
        a.admit(per_g[g], np.full(60, g, np.int32))
    for start in range(0, 60, 10):
        for g in (2, 0, 1):
            b.admit(per_g[g][start:start + 10], np.full(10, g, np.int32))
    for g in range(3):
        np.testing.assert_array_equal(a.rows_for(g), b.rows_for(g))
        assert a.count[g] == b.count[g] == 16
        assert a.seen[g] == b.seen[g] == 60


def test_buffer_admits_only_normal_verdicts_and_clears():
    buf = FlywheelBuffer(2, DIM, capacity=8, seed=0)
    rows = np.arange(6 * DIM, dtype=np.float32).reshape(6, DIM)
    verdicts = np.asarray([False, True, False, True, True, False])
    n = buf.admit(rows, np.zeros(6, np.int32), verdicts=verdicts)
    assert n == 3 and buf.count[0] == 3 and buf.seen[0] == 3
    kept = buf.rows_for(0)
    for row in kept:  # every kept row was a normal-verdicted one
        assert any(np.array_equal(row, rows[i]) for i in (0, 2, 5))
    buf.clear()
    assert buf.count[0] == 0 and buf.rows_for(0).shape == (0, DIM)


def test_finetune_data_masks_and_eligibility():
    buf = FlywheelBuffer(3, DIM, capacity=32, seed=0)
    rng = np.random.default_rng(0)
    buf.admit(rng.normal(size=(30, DIM)).astype(np.float32),
              np.zeros(30, np.int32))
    buf.admit(rng.normal(size=(4, DIM)).astype(np.float32),
              np.full(4, 1, np.int32))  # below min_rows
    member = np.asarray([True, True, False])  # gateway 2 left the roster
    ft = buf.build_finetune_data(8, np.zeros((5, DIM), np.float32),
                                 valid_frac=0.25, min_rows=8, member=member)
    assert ft.eligible.tolist() == [True, False, False]
    d = ft.data
    assert d.client_mask.tolist() == [1.0, 0.0, 0.0]
    # ineligible gateways carry ZERO row masks everywhere
    for leaf in (d.train_mb, d.valid_mb, d.valid_m, d.test_m):
        assert float(np.sum(np.asarray(leaf)[1:])) == 0.0
    # the eligible gateway's split covers all its rows exactly once
    assert len(ft.train_rows[0]) + len(ft.valid_rows[0]) == 30
    assert float(np.sum(np.asarray(d.train_mb)[0])) == len(ft.train_rows[0])
    assert float(np.sum(np.asarray(d.valid_m)[0])) == len(ft.valid_rows[0])


# --------------------- flywheel-off bit-identity ----------------------- #

def test_flywheel_off_bit_identical_to_plain_front():
    """Pin (a): no intake == the pre-flywheel front, byte for byte; and
    an ATTACHED but never-triggering tap changes no score/verdict byte
    either (it only observes harvested arrays)."""
    _, _, _, eng, cal, rows, gws = _setup()
    plain = ContinuousBatcher(eng, max_batch=32, latency_budget_ms=1e9,
                              calibration=cal)
    t_plain = [plain.submit(rows[i], gws[i]) for i in range(300)]
    plain.drain()

    buf = FlywheelBuffer(N, DIM, capacity=64, seed=0)
    tapped = ContinuousBatcher(eng, max_batch=32, latency_budget_ms=1e9,
                               calibration=cal, intake=buf.tap())
    t_tap = [tapped.submit(rows[i], gws[i]) for i in range(300)]
    tapped.drain()

    np.testing.assert_array_equal(
        np.asarray([t.score for t in t_plain], np.float32),
        np.asarray([t.score for t in t_tap], np.float32))
    assert [t.verdict for t in t_plain] == [t.verdict for t in t_tap]
    assert plain.stats()["dispatches"] == tapped.stats()["dispatches"]
    # the tap actually observed the stream (normal-verdicted rows only)
    assert buf.seen.sum() > 0
    # ... and the no-intake record retained no row buffers
    assert plain._inflight is None and tapped._inflight is None


# ----------------------- candidate-state scoring ----------------------- #

def test_score_candidate_matches_install_and_leaves_resident_untouched():
    model, params, train_x, eng, cal, rows, gws = _setup()
    params2 = init_stacked_params(model, jax.random.key(9), N)
    before = eng.score(rows[:64], gws[:64])
    cand = eng.candidate_state(params=params2)
    got = eng.score_candidate(cand, rows[:64], gws[:64])
    # resident state untouched by the candidate pass
    np.testing.assert_array_equal(eng.score(rows[:64], gws[:64]), before)
    assert eng.swap_count == 0
    eng.swap_state(params=params2)
    np.testing.assert_allclose(got, eng.score(rows[:64], gws[:64]),
                               atol=1e-6)
    with pytest.raises(ValueError, match="nothing replaced"):
        eng.candidate_state()


def test_refit_calibration_matches_chained_refit():
    _, _, _, eng, cal, rows, gws = _setup()
    rng = np.random.default_rng(1)
    scores = {0: rng.normal(size=40), 2: rng.normal(size=25)}
    vec = refit_calibration(cal, scores)
    chained = cal.refit(0, scores[0]).refit(2, scores[2])
    np.testing.assert_array_equal(vec.thresholds, chained.thresholds)
    np.testing.assert_array_equal(vec.mean, chained.mean)
    np.testing.assert_array_equal(vec.std, chained.std)
    np.testing.assert_array_equal(vec.count, chained.count)
    # untouched gateways keep the incumbent calibration
    assert vec.thresholds[1] == cal.thresholds[1]


# ------------------------- drift monitor knobs ------------------------- #

def test_drift_cooldown_suppresses_recommendation_and_reports():
    _, _, _, eng, cal, _, _ = _setup()
    mon = DriftMonitor(cal, z_threshold=0.5, min_count=10, min_batches=2,
                       cooldown_updates=3)
    assert mon.report()["last_rebaseline"] is None
    hot = cal.mean[0] + 50 * (cal.std[0] + 1.0)  # unmistakable shift
    for _ in range(4):
        mon.update(np.full(20, hot), np.zeros(20, np.int32))
    assert mon.swap_recommended()[0]
    upd = mon.updates
    mon.rebaseline(cal)
    assert mon.report()["last_rebaseline"] == upd
    # drifted again immediately — but the cooldown suppresses the
    # RECOMMENDATION (not the detection) for 3 traffic-carrying updates
    for i in range(3):
        mon.update(np.full(20, hot), np.zeros(20, np.int32))
        assert not mon.swap_recommended()[0], f"update {i} in cooldown"
    assert mon.report()["gateways"][0]["drifted"]  # detection kept seeing it
    mon.update(np.full(20, hot), np.zeros(20, np.int32))
    assert mon.swap_recommended()[0]  # cooldown over, streak sustained


# --------------------- mid-load full-payload swap ---------------------- #

def test_full_payload_swap_mid_load_zero_drops_and_atomic():
    """Pin (b): a flywheel-shaped swap (params + banks + thresholds in
    ONE call) lands between dispatches of a live stream with zero
    dropped/duplicated tickets, old-regime batches verdicted under the
    old calibration and new-regime batches under the new."""
    model, params, train_x, eng, cal, rows, gws = _setup(score_kind="knn")
    from fedmse_tpu.knn import build_banks
    params2 = init_stacked_params(model, jax.random.key(9), N)
    banks2 = build_banks(model, params2, train_x, bank_size=32)
    always = refit_calibration(cal, {g: np.asarray([1e9])
                                     for g in range(N)})  # never flags

    front = ContinuousBatcher(eng, max_batch=16, latency_budget_ms=1e9,
                              calibration=cal)
    pre = [front.submit(rows[i], gws[i]) for i in range(24)]  # 16 in flight
    cache = eng._score_fn._cache_size()
    event = front.swap(params=params2, banks=banks2, calibration=always)
    post = [front.submit(rows[i], gws[i]) for i in range(24, 48)]
    front.drain()
    assert sorted(event["kinds"]) == ["banks", "params", "thresholds"]
    assert eng._score_fn._cache_size() == cache  # zero retrace
    assert all(t.done for t in pre + post)
    st = front.stats()
    assert st["rows_served"] == st["rows_submitted"] == 48
    # batch 1 (in flight at swap) scored under the OLD state + thresholds
    eng_old = ServingEngine.from_federation(
        model, "autoencoder", params, train_x=train_x, score_kind="knn",
        knn_bank_size=32, max_bucket=64)
    np.testing.assert_allclose([t.score for t in pre[:16]],
                               eng_old.score(rows[:16], gws[:16]), atol=1e-5)
    want_pre = cal.verdicts(eng_old.score(rows[:16], gws[:16]), gws[:16])
    assert [t.verdict for t in pre[:16]] == list(want_pre)
    # everything after the swap: new params+banks, thresholds never flag
    np.testing.assert_allclose([t.score for t in post],
                               eng.score(rows[24:48], gws[24:48]), atol=1e-5)
    assert not any(t.verdict for t in pre[16:] + post)


# ------------------------- end-to-end recovery ------------------------- #

def _manifold_regime(seed, dim, rank=2, noise=0.2):
    rng = np.random.default_rng(seed)
    w = rng.normal(size=(rank, dim))
    w /= np.linalg.norm(w, axis=1, keepdims=True)
    q, _ = np.linalg.qr(w.T)
    u = rng.normal(size=dim)
    u -= q @ (q.T @ u)
    u /= np.linalg.norm(u)

    def normals(rng_, n, shift=0.0):
        x = rng_.normal(size=(n, rank)) @ w \
            + noise * rng_.normal(size=(n, dim))
        return (x + shift * u).astype(np.float32)

    return normals, u


def test_flywheel_end_to_end_recovery():
    """The loop: train -> serve -> inject shift -> buffer fills ->
    fine-tune fires -> swap lands -> detection recovers while the frozen
    engine degrades. Reduced-scale twin of drift_recovery_sweep.py."""
    import pandas as pd

    from fedmse_tpu.data import build_dev_dataset, stack_clients
    from fedmse_tpu.data.loader import ClientData
    from fedmse_tpu.federation import RoundEngine
    from fedmse_tpu.parallel import host_fetch
    from fedmse_tpu.utils.seeding import ExperimentRngs

    dim, n_clients, behind = 10, 3, 1.25
    normals, u = _manifold_regime(0, dim)
    rng = np.random.default_rng(1)
    cfg = ExperimentConfig(network_size=n_clients, dim_features=dim,
                           epochs=4, num_rounds=2, batch_size=12)
    clients = [ClientData(
        name=f"fw-{i}", train_x=normals(rng, 160),
        valid_x=normals(rng, 48),
        test_x=normals(rng, 24), test_y=np.zeros(24, np.float32),
        dev_raw=pd.DataFrame(normals(rng, 60)), scaler=None)
        for i in range(n_clients)]
    data = stack_clients(clients,
                         build_dev_dataset(clients,
                                           ExperimentRngs(run=0).data_rng),
                         cfg.batch_size)
    model = make_model("autoencoder", dim)
    trainer = RoundEngine(model, cfg, data, n_real=n_clients,
                          rngs=ExperimentRngs(run=0),
                          model_type="autoencoder", update_type="mse_avg",
                          fused=True)
    trainer.run_rounds(0, cfg.num_rounds)
    params = host_fetch(trainer.states.params)

    def build_serving():
        return ServingEngine.from_federation(
            model, "autoencoder", params,
            train_x=np.asarray(data.train_xb),
            train_m=np.asarray(data.train_mb), max_bucket=64)

    engine, frozen = build_serving(), build_serving()
    calib = fit_calibration(engine, np.asarray(data.valid_x),
                            np.asarray(data.valid_m), percentile=99.0)
    monitor = DriftMonitor(calib, z_threshold=0.5, min_batches=2,
                           cooldown_updates=2)
    buf = FlywheelBuffer(n_clients, dim, capacity=128, seed=0)
    front = ContinuousBatcher(engine, max_batch=32, latency_budget_ms=1e9,
                              calibration=calib, drift=monitor,
                              intake=buf.tap())
    controller = FlywheelController(
        front, monitor, buf, model, "autoencoder", "mse_avg", cfg,
        dev_x=np.asarray(data.dev_x), rounds=2, quorum=2, cooldown_polls=2,
        min_rows=48)

    def eval_auc(score_fn, shift):
        r = np.random.default_rng(99)
        xs = np.concatenate([normals(r, 96, shift),
                             normals(r, 96, -behind)])
        ys = np.concatenate([np.zeros(96), np.ones(96)])
        g = np.tile(np.arange(n_clients, dtype=np.int32),
                    -(-len(xs) // n_clients))[:len(xs)]
        return host_auc(ys, score_fn(xs, g))

    auc_pre = eval_auc(engine.score, 0.0)
    gws = np.tile(np.arange(n_clients, dtype=np.int32), 96)
    blocks = []
    for shift in (0.0, 0.6, 1.2, 1.8, 1.8):  # ramp, then hold
        fresh = normals(rng, 96 * n_clients, shift)
        bs, _ = stream_with_polling(front, controller, fresh, gws,
                                    chunk=32)
        blocks.extend(bs)

    assert len(controller.events) >= 1, "fine-tune never fired"
    for event in controller.events:
        assert "params" in event["kinds"] and "thresholds" in event["kinds"]
    integ = ticket_integrity(blocks)
    assert integ["zero_dropped"], integ
    st = front.stats()
    assert st["rows_served"] == st["rows_submitted"]
    auc_live = eval_auc(engine.score, 1.8)
    auc_frozen = eval_auc(frozen.score, 1.8)
    assert auc_frozen < auc_pre - 0.1, (auc_pre, auc_frozen)
    assert auc_live > auc_frozen + 0.2, (auc_live, auc_frozen)
    assert auc_live > 0.85, auc_live
    # the monitor was rebaselined by the swap and says so
    assert monitor.report()["last_rebaseline"] is not None


def test_controller_backs_off_on_empty_buffer():
    """A sustained drift verdict with an empty reservoir must NOT train:
    the controller logs, backs off, and swaps nothing."""
    model, params, train_x, eng, cal, rows, gws = _setup()
    mon = DriftMonitor(cal, z_threshold=0.5, min_count=10, min_batches=1)
    buf = FlywheelBuffer(N, DIM, capacity=32, seed=0)
    front = ContinuousBatcher(eng, max_batch=16, latency_budget_ms=1e9,
                              calibration=cal, drift=mon)
    cfg = ExperimentConfig(network_size=N, dim_features=DIM)
    ctl = FlywheelController(front, mon, buf, model, "autoencoder",
                             "mse_avg", cfg, dev_x=np.zeros((4, DIM)),
                             quorum=1, cooldown_polls=3, min_rows=16)
    hot = cal.mean + 50 * (cal.std + 1.0)
    for g in range(N):
        mon.update(np.full(20, hot[g]), np.full(20, g, np.int32))
    assert mon.swap_recommended().any()
    assert ctl.poll() is None          # trigger suppressed: empty buffer
    assert not ctl.events and eng.swap_count == 0
    assert ctl._cooldown == 3          # backed off, not spinning


# ------------------------- async fine-tune ----------------------------- #

def test_async_finetune_serves_while_training_and_installs_atomically():
    """background=True: the trigger hands the fine-tune to the executor
    and polls return immediately; serving keeps submitting AND
    harvesting throughout; the completed payload installs through ONE
    atomic swap on a later poll, with zero dropped tickets."""
    import threading

    model, params, train_x, eng, cal, rows, gws = _setup()
    mon = DriftMonitor(cal, z_threshold=1e9)  # never recommends: the
    buf = FlywheelBuffer(N, DIM, capacity=64, seed=0)  # test drives
    front = ContinuousBatcher(eng, max_batch=16, latency_budget_ms=1e9,
                              calibration=cal, drift=mon, intake=buf.tap())
    cfg = ExperimentConfig(network_size=N, dim_features=DIM)
    ctl = FlywheelController(front, mon, buf, model, "autoencoder",
                             "mse_avg", cfg, dev_x=np.zeros((4, DIM)),
                             quorum=1, min_rows=16, background=True)
    buf.admit(rows[:200], gws[:200])  # all gateways over min_rows

    gate = threading.Event()
    started = threading.Event()
    incumbent = jax.device_get(eng.params)

    def slow_finetune(finetune):
        started.set()
        assert gate.wait(30.0), "test gate never opened"
        return (jax.tree.map(lambda t: np.asarray(t, np.float32),
                             incumbent), [{"round": 0}])

    ctl._finetune = slow_finetune
    assert ctl.trigger(np.asarray([0])) is None  # dispatched, not done
    assert ctl.finetune_pending
    assert started.wait(30.0)

    # serving continues WHILE the fine-tune runs: full round-trips,
    # submit -> dispatch -> harvest, with the controller polled between
    blk = front.submit_many(rows[:48], gws[:48])
    front.drain()
    assert ctl.poll() is None and ctl.finetune_pending
    assert blk.done and blk.scores is not None
    np.testing.assert_allclose(blk.scores, eng.score(rows[:48], gws[:48]),
                               atol=1e-5)
    assert eng.swap_count == 0  # nothing installed mid-flight

    gate.set()
    event = ctl.wait(30.0)  # deterministic completion for the test;
    assert event is not None  # a deployment keeps poll()ing instead
    assert not ctl.finetune_pending
    assert event["flywheel"]["finetune_async"] is True
    assert "params" in event["kinds"] and "thresholds" in event["kinds"]
    assert eng.swap_count == 1 and ctl.events == [event]
    # post-install hygiene matches the synchronous path
    assert ctl._cooldown == ctl.cooldown_polls
    assert buf.count.sum() == 0  # clear_on_swap consumed the reservoirs
    # and the front still serves, under the installed regime
    blk2 = front.submit_many(rows[48:80], gws[48:80])
    front.drain()
    assert blk2.done
    st = front.stats()
    assert st["rows_served"] == st["rows_submitted"]


def test_async_finetune_blocks_second_trigger_until_installed():
    """While a background fine-tune is pending, neither poll() nor a
    direct trigger() may launch a second one."""
    import threading

    model, params, train_x, eng, cal, rows, gws = _setup()
    mon = DriftMonitor(cal, z_threshold=0.5, min_count=10, min_batches=1)
    buf = FlywheelBuffer(N, DIM, capacity=64, seed=0)
    front = ContinuousBatcher(eng, max_batch=16, latency_budget_ms=1e9,
                              calibration=cal, drift=mon)
    cfg = ExperimentConfig(network_size=N, dim_features=DIM)
    ctl = FlywheelController(front, mon, buf, model, "autoencoder",
                             "mse_avg", cfg, dev_x=np.zeros((4, DIM)),
                             quorum=1, min_rows=16, background=True)
    buf.admit(rows[:200], gws[:200])
    gate = threading.Event()
    calls = []
    incumbent = jax.device_get(eng.params)

    def slow_finetune(finetune):
        calls.append(1)
        gate.wait(30.0)
        return (jax.tree.map(lambda t: np.asarray(t, np.float32),
                             incumbent), [])

    ctl._finetune = slow_finetune
    # a sustained recommendation keeps the streak hot on every poll
    hot = cal.mean + 50 * (cal.std + 1.0)
    for g in range(N):
        mon.update(np.full(20, hot[g]), np.full(20, g, np.int32))
    assert ctl.poll() is None and ctl.finetune_pending  # launched once
    for _ in range(5):
        mon.update(np.full(20, hot[0]), np.zeros(20, np.int32))
        assert ctl.poll() is None  # pending gates re-trigger
    assert ctl.trigger(np.asarray([0])) is None  # direct trigger gated too
    assert len(calls) == 1
    gate.set()
    assert ctl.wait(30.0) is not None
    assert len(calls) == 1 and eng.swap_count == 1


# ----------------------- recency-weighted decay ------------------------ #

def test_decay_reservoir_prefers_recent_rows():
    """decay<1 biases retention exponentially toward recent admissions
    (the clear-on-swap alternative for continuous drift); the uniform
    default keeps sampling the whole history."""
    t = 400
    stream = np.zeros((t, DIM), np.float32)
    stream[:, 0] = np.arange(t)  # feature 0 encodes the admission index
    uni = FlywheelBuffer(1, DIM, capacity=16, seed=0)
    dec = FlywheelBuffer(1, DIM, capacity=16, seed=0, decay=0.5)
    for start in range(0, t, 25):  # same stream, batched admission
        uni.admit(stream[start:start + 25], np.zeros(25, np.int32))
        dec.admit(stream[start:start + 25], np.zeros(25, np.int32))
    kept_uni = np.sort(uni.rows_for(0)[:, 0])
    kept_dec = np.sort(dec.rows_for(0)[:, 0])
    assert len(kept_uni) == len(kept_dec) == 16
    # decay 0.5: a row d admissions old survives with weight 2^-d — the
    # reservoir is essentially the most recent rows
    assert kept_dec.min() >= t - 32
    assert kept_dec.mean() > t - 20
    # the uniform reservoir keeps sampling the whole stream
    assert kept_uni.min() < t // 2
    assert kept_uni.mean() < kept_dec.mean() - 100


def test_decay_reservoir_padding_and_layout_invariant():
    """The decayed priority is a pure function of (seed, g, j) with g
    the ABSOLUTE gateway index and j the admission ordinal — so the
    PARITY.md §8 invariance holds for the decay path exactly like the
    uniform one."""
    rng = np.random.default_rng(3)
    per_g = {g: rng.normal(size=(60, DIM)).astype(np.float32)
             for g in range(3)}
    a = FlywheelBuffer(3, DIM, capacity=16, seed=5, decay=0.9)
    b = FlywheelBuffer(9, DIM, capacity=16, seed=5, decay=0.9)
    for g in range(3):
        a.admit(per_g[g], np.full(60, g, np.int32))
    for start in range(0, 60, 10):  # interleaved, wider axis
        for g in (2, 0, 1):
            b.admit(per_g[g][start:start + 10], np.full(10, g, np.int32))
    for g in range(3):
        np.testing.assert_array_equal(a.rows_for(g), b.rows_for(g))
    # a post-clear stream keeps decaying from the ABSOLUTE ordinal: the
    # cleared gateway's retention stays deterministic and recent-biased
    a.clear([0])
    b.clear([0])
    more = rng.normal(size=(20, DIM)).astype(np.float32)
    a.admit(more, np.zeros(20, np.int32))
    b.admit(more, np.zeros(20, np.int32))
    np.testing.assert_array_equal(a.rows_for(0), b.rows_for(0))


def test_decay_validation():
    with pytest.raises(ValueError, match="decay"):
        FlywheelBuffer(1, DIM, decay=0.0)
    with pytest.raises(ValueError, match="decay"):
        FlywheelBuffer(1, DIM, decay=1.5)
    FlywheelBuffer(1, DIM, decay=1.0)  # λ=1: unweighted, valid


def test_async_finetune_failure_clears_pending_and_reraises():
    """A failed background fine-tune must not gate the controller
    forever: wait()/poll() clear the pending slot and re-raise."""
    model, params, train_x, eng, cal, rows, gws = _setup()
    mon = DriftMonitor(cal, z_threshold=1e9)
    buf = FlywheelBuffer(N, DIM, capacity=64, seed=0)
    front = ContinuousBatcher(eng, max_batch=16, latency_budget_ms=1e9,
                              calibration=cal)
    cfg = ExperimentConfig(network_size=N, dim_features=DIM)
    ctl = FlywheelController(front, mon, buf, model, "autoencoder",
                             "mse_avg", cfg, dev_x=np.zeros((4, DIM)),
                             quorum=1, min_rows=16, background=True)
    buf.admit(rows[:200], gws[:200])

    def broken_finetune(finetune):
        raise RuntimeError("synthetic fine-tune failure")

    ctl._finetune = broken_finetune
    assert ctl.trigger(np.asarray([0])) is None
    with pytest.raises(RuntimeError, match="synthetic fine-tune"):
        ctl.wait(30.0)
    assert not ctl.finetune_pending  # slot cleared: the loop can retry
    assert eng.swap_count == 0 and ctl.events == []
