"""Pod-scale host-sharded federation (DESIGN.md §20, federation/tiered.py
host_sharded=True, checkpointing/io.py pod-sharded snapshots).

Pins, in dependency order:
  * single-process host-sharded tier (H=1: the one block covers the fleet)
    is BITWISE the plain tiered engine — states, per-round results and the
    streamed final evaluation;
  * ClusterSpec.refit_every is live on the tiered path (it was inert,
    fit-once, before PR 16): the dense due-logic cadence, keyed to the
    round the incumbent vector was fitted at, and the sharded fit produces
    the plain fit's assignment;
  * the REAL 2-process pod run (tests/multihost_worker.py mode 'podtier'
    via the session worker-pair) agrees across processes and lands within
    the documented AUC bar of the same-seed single-process run;
  * pod checkpoints are layout-interchangeable: shards saved at H=2
    reassemble identically at any [start, stop), and a single-process run
    (plain AND host-sharded) resumes from them.
"""

import os

import numpy as np
import pytest

import jax
import optax

from multihost_launcher import match_all
from multihost_worker import podtier_config, podtier_federation
from test_tiered import (_assert_states_equal, _cfg, _federation, _run,
                         _tiered)

pytestmark = pytest.mark.podscale

POD_TAG = "hybrid_mse_avg_run0"  # the worker's run_tiered_combination tag


# ------------------ single-process H=1 degeneration -------------------- #

def test_host_sharded_single_process_bitwise_plain(mesh8):
    """host_sharded=True on one process: one tier block covering the
    fleet, the stratified draw degenerating to the plain draw, the lane
    plan to the sorted prefix — every round result, the final store and
    the streamed evaluation byte-match the plain tiered engine."""
    cfg = _cfg(num_rounds=3)
    _, data = _federation(10, cfg)
    plain = _tiered(cfg, data, 10, mesh=mesh8)
    shard = _tiered(cfg, data, 10, mesh=mesh8, host_sharded=True)
    assert shard.sharded and shard._fleet_local
    assert (shard.shard_start, shard.shard_stop) == (0, 10)

    for rp, rs in zip(_run(plain, 3), _run(shard, 3)):
        assert rp.aggregator == rs.aggregator
        np.testing.assert_array_equal(rp.selected, rs.selected)
        np.testing.assert_array_equal(rp.client_metrics, rs.client_metrics)
    _assert_states_equal(plain.store.host, shard.store.host)
    np.testing.assert_array_equal(plain.evaluate_final_streamed(),
                                  shard.evaluate_final_streamed())


# --------------------- refit_every on the tier ------------------------- #

def _count_fits(engine):
    calls = []
    orig = engine._fit_cluster

    def counted():
        fit = orig()
        calls.append(fit.assignment.copy())
        return fit

    engine._fit_cluster = counted
    return calls


def test_cluster_refit_every_is_live_on_tier(mesh8):
    """refit_every=2 over 5 rounds refits at rounds 0, 2 and 4 (the dense
    due-logic: round - fitted_round >= refit_every); refit_every=0 stays
    fit-once. The sharded H=1 fit reproduces the plain fit's assignment —
    the probe and the per-block stats merge are keyed to ABSOLUTE gateway
    ids, so the tiling is invisible to the clustering."""
    from fedmse_tpu.cluster import ClusterSpec

    spec = ClusterSpec(k=2, refit_every=2)
    cfg = _cfg(num_rounds=5)
    _, data = _federation(10, cfg)

    plain = _tiered(cfg, data, 10, mesh=mesh8, cluster=spec)
    fits_p = _count_fits(plain)
    shard = _tiered(cfg, data, 10, mesh=mesh8, cluster=spec,
                    host_sharded=True)
    fits_s = _count_fits(shard)
    _run(plain, 5)
    _run(shard, 5)
    assert len(fits_p) == len(fits_s) == 3  # rounds 0, 2, 4
    assert plain._cluster_fitted_round == shard._cluster_fitted_round == 4
    for fp, fs in zip(fits_p, fits_s):
        np.testing.assert_array_equal(fp, fs)

    once = _tiered(cfg, data, 10, mesh=mesh8,
                   cluster=ClusterSpec(k=2, refit_every=0))
    fits_once = _count_fits(once)
    _run(once, 5)
    assert len(fits_once) == 1  # fit-once stays fit-once


# ----------------------- real 2-process pod ---------------------------- #

def test_two_process_pod_tier_agrees(two_process_outputs):
    """mode 'podtier' in the session worker pair: each process tiers only
    its 6 of 12 clients, rounds run over the cross-host cohort assembly
    and the lane-block scatter, and BOTH processes print the identical
    digest — the shared host streams and allgathered outputs keep the
    control plane uniform with zero coordination messages."""
    results = match_all(
        two_process_outputs.outs,
        r"PODTIER_OK pid=\d+ (best=[\d.]+ mean=[\d.]+ agg=\[[^\]]*\])")
    assert results[0].group(1) == results[1].group(1)


def test_pod_matches_single_process_auc(two_process_outputs):
    """The vs-single-process quality bar (ISSUE 16 acceptance): the
    2-process host-sharded run's final metrics land within 2e-3 AUC of
    the SAME scenario run single-process at the same seed. Not bitwise —
    the pod evaluates over the 2-process mesh with its own reduction
    order — but the federation it converges to is the same."""
    match_all(two_process_outputs.outs, r"PODTIER_OK pid=\d+")
    pod = np.load(os.path.join(two_process_outputs.outdir,
                               "pod_result_0.npz"))
    from fedmse_tpu.federation.tiered import run_tiered_combination

    cfg, dim, n_real = podtier_config()
    data = podtier_federation(cfg, dim, n_real)
    ref = run_tiered_combination(cfg, data, n_real, "hybrid", "mse_avg", 0)
    assert abs(float(pod["best_final"]) - ref["best_final"]) <= 2e-3
    np.testing.assert_allclose(pod["final_metrics"],
                               ref["final_metrics"], atol=2e-3)


# ------------------ pod checkpoints across layouts --------------------- #

def _states_like(cfg, n_rows=1):
    from fedmse_tpu.federation import init_client_states
    from fedmse_tpu.models import make_model

    model = make_model("hybrid", cfg.dim_features, cfg.hidden_neus,
                       cfg.latent_dim, cfg.shrink_lambda)
    return jax.device_get(init_client_states(
        model, optax.adam(cfg.lr_rate), jax.random.key(0), n_rows))


def test_pod_checkpoint_restores_across_layouts(two_process_outputs, mesh8):
    """Satellite 4: the checkpoint the 2-process pod wrote (H=2 shards of
    6 rows) reassembles at ANY layout — the dense [0, 12) restore byte-
    matches the concatenation of the two per-host restores, and both a
    plain single-process tiered run and a host-sharded (H=1) one resume
    from it at round 3 (no rounds left) with the pod's federation."""
    from fedmse_tpu.checkpointing.io import CheckpointManager
    from fedmse_tpu.federation.tiered import run_tiered_combination

    match_all(two_process_outputs.outs, r"PODTIER_OK pid=\d+")
    mgr = CheckpointManager(str(two_process_outputs.outdir / "podckpt"))
    assert mgr.exists_sharded(POD_TAG)

    cfg, dim, n_real = podtier_config()
    like = _states_like(cfg)
    dense, host, rnd, _ = mgr.restore_sharded(POD_TAG, like, 0, n_real)
    assert rnd == cfg.num_rounds
    lo_states, lo_host, _, _ = mgr.restore_sharded(POD_TAG, like, 0, 6)
    hi_states, hi_host, _, _ = mgr.restore_sharded(POD_TAG, like, 6, n_real)
    for full, lo, hi in zip(jax.tree.leaves(dense),
                            jax.tree.leaves(lo_states),
                            jax.tree.leaves(hi_states)):
        np.testing.assert_array_equal(full,
                                      np.concatenate([lo, hi], axis=0))
    # HostState is fleet-wide in the manifest: identical at every slice
    np.testing.assert_array_equal(host.aggregation_count,
                                  lo_host.aggregation_count)
    np.testing.assert_array_equal(host.votes_received,
                                  hi_host.votes_received)

    # both single-process layouts resume the pod snapshot: all rounds are
    # done, so the run is pure restore + final evaluation
    data = podtier_federation(cfg, dim, n_real)
    outs = {}
    for name, kw in (("plain", {}), ("sharded", {"host_sharded": True})):
        out = run_tiered_combination(cfg.replace(**kw), data, n_real,
                                     "hybrid", "mse_avg", 0, mesh=mesh8,
                                     resume=mgr)
        assert out["round_times"] == []  # resumed at round 3 of 3
        outs[name] = np.asarray(out["final_metrics"])
    # H=1 sharded is bitwise the plain engine — restores included
    np.testing.assert_array_equal(outs["plain"], outs["sharded"])
    pod = np.load(os.path.join(two_process_outputs.outdir,
                               "pod_result_0.npz"))
    np.testing.assert_allclose(outs["plain"], pod["final_metrics"],
                               atol=2e-3)
