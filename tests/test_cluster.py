"""Clustered + personalized federation (fedmse_tpu/cluster/, DESIGN.md §19)
with the acceptance contracts pinned:

  * the jax Gaussian-KL/JS port matches the numpy oracle
    (utils/similarity.py) at float32 tolerance — the assignment metric's
    parity pin;
  * a null ClusterSpec (k=1, no personalization) lowers to the EXACT
    single-global program: states, metrics and artifacts bit-identical
    on CPU (by construction — the cluster branches do not trace);
  * assignments are padding/layout-invariant (absolute gateway ids,
    PARITY.md §8) and the JS k-medoids fit is deterministic;
  * verification/broadcast scope to the voter's cluster: after an
    accepted round every client holds ITS cluster's merge, clusters
    never bleed into each other, and personalization keeps per-gateway
    decoders local;
  * elastic joins recycle from the NEAREST cluster's incumbent mean;
  * serving routes each gateway to its cluster model
    (cluster.cluster_models parity vs a per-cluster oracle) and a
    cluster-model hot swap is zero-retrace (_cache_size pin) with the
    roster's cluster column riding along;
  * checkpoint round-trip of the assignment, with a CLEAR error on a K
    change.
"""

import numpy as np
import pytest

import jax

from fedmse_tpu.cluster import (ClusterAssignment, ClusterSpec,
                                assignment_from_extra, cluster_models,
                                fit_assignments, fit_medoids, gaussian_js,
                                gaussian_kl, make_latent_stats_fn,
                                pairwise_js, personalized_broadcast)
from fedmse_tpu.config import CompatConfig, ExperimentConfig
from fedmse_tpu.data import build_dev_dataset, stack_clients
from fedmse_tpu.data.synthetic import synthetic_clients
from fedmse_tpu.federation import ElasticSpec, RoundEngine
from fedmse_tpu.models import make_model
from fedmse_tpu.utils.seeding import ExperimentRngs
from fedmse_tpu.utils.similarity import js_divergence, kl_divergence

pytestmark = pytest.mark.cluster

DIM = 12
N = 6


def build_cfg(**kw):
    return ExperimentConfig(
        dim_features=DIM, network_size=N, epochs=2, batch_size=8,
        hidden_neus=8, latent_dim=4,
        compat=CompatConfig(vote_tie_break=False), **kw)


def build_data(cfg, pad_to=None, seed=3):
    clients = synthetic_clients(n_clients=N, dim=DIM, n_normal=120,
                                n_abnormal=60, seed=seed, noniid=True)
    dev_x = build_dev_dataset(clients, ExperimentRngs(run=0).data_rng)
    return stack_clients(clients, dev_x, cfg.batch_size,
                         pad_clients_to=pad_to)


def build_engine(cfg, data, cluster=None, elastic=None, run=0,
                 update_type="mse_avg"):
    m = make_model("hybrid", DIM, cfg.hidden_neus, cfg.latent_dim,
                   shrink_lambda=cfg.shrink_lambda)
    return RoundEngine(m, cfg, data, n_real=N, rngs=ExperimentRngs(run=run),
                       model_type="hybrid", update_type=update_type,
                       fused=True, cluster=cluster, elastic=elastic)


def _rand_gaussians(rng, g, latent):
    means = rng.normal(size=(g, latent)).astype(np.float32)
    q = rng.normal(size=(g, latent, latent))
    covs = (np.einsum("gij,gkj->gik", q, q) / latent
            + 0.1 * np.eye(latent)).astype(np.float32)
    return means, covs


# ----------------------------------------------------------------- spec ----

def test_spec_validation():
    with pytest.raises(ValueError, match="k must be"):
        ClusterSpec(k=0)
    with pytest.raises(ValueError, match="refit_every"):
        ClusterSpec(k=2, refit_every=-1)
    # the KDE seam is documented, not wired: asking for it names PARITY §9
    with pytest.raises(ValueError, match="PARITY.md"):
        ClusterSpec(k=2, metric="kde")
    with pytest.raises(ValueError, match="shared module"):
        ClusterSpec(k=2, personalize=True, shared_modules=())
    assert ClusterSpec(k=1).is_null
    assert not ClusterSpec(k=1, personalize=True).is_null
    assert ClusterSpec(k=2).signature() != ClusterSpec(k=4).signature()


# ------------------------------------------------ similarity parity pin ----

def test_kl_js_jax_matches_numpy_oracle(rng):
    """The satellite parity pin: the on-device Gaussian-KL/JS port agrees
    with the numpy implementation (utils/similarity.py — the oracle, f64
    quadratic form) at float32 tolerance on random SPD covariances."""
    means, covs = _rand_gaussians(rng, 6, 5)
    for i in range(6):
        for j in range(6):
            ref_kl = kl_divergence(means[i].astype(np.float64),
                                   covs[i].astype(np.float64),
                                   means[j].astype(np.float64),
                                   covs[j].astype(np.float64))
            got_kl = float(gaussian_kl(means[i], covs[i], means[j], covs[j]))
            assert abs(ref_kl - got_kl) <= 1e-3 * max(1.0, abs(ref_kl))
            ref_js = js_divergence(means[i].astype(np.float64),
                                   covs[i].astype(np.float64),
                                   means[j].astype(np.float64),
                                   covs[j].astype(np.float64))
            got_js = float(gaussian_js(means[i], covs[i], means[j], covs[j]))
            assert abs(ref_js - got_js) <= 1e-3 * max(1.0, abs(ref_js))
    # the batched pairwise matrix is the same math, one dispatch
    mat = np.asarray(pairwise_js(means, covs))
    assert mat.shape == (6, 6)
    assert abs(mat[1, 4] - float(gaussian_js(means[1], covs[1],
                                             means[4], covs[4]))) < 1e-4
    # JS is symmetric and ~0 on the diagonal
    np.testing.assert_allclose(mat, mat.T, atol=1e-3)
    assert np.abs(np.diag(mat)).max() < 1e-3


# ---------------------------------------------------------------- fitter ----

def test_fit_medoids_groups_and_determinism(rng):
    """Two well-separated synthetic groups cluster cleanly, the fit is a
    pure function of the matrix, and the pooled-Gaussian consistency
    metric (the churn-composition acceptance rate) is perfect here."""
    g = 8
    means = np.zeros((g, 3), np.float32)
    means[4:] += 25.0  # two far groups
    covs = np.tile(0.5 * np.eye(3, dtype=np.float32), (g, 1, 1))
    means += rng.normal(scale=0.1, size=means.shape).astype(np.float32)
    fit = fit_assignments(means, covs, k=2)
    a = fit.assignment
    assert len(set(a[:4])) == 1 and len(set(a[4:])) == 1
    assert a[0] != a[4]
    fit2 = fit_assignments(means, covs, k=2)
    assert np.array_equal(a, fit2.assignment)  # deterministic
    assert fit.consistency() == 1.0
    # k >= G degenerates to singletons without error
    a_all, _ = fit_medoids(np.asarray(pairwise_js(
        jax.numpy.asarray(means), jax.numpy.asarray(covs))), k=16)
    assert len(set(a_all.tolist())) == g


def test_fit_sample_caps_medoid_fit(rng):
    """The pod-scale fit cap (ClusterSpec.fit_sample, the CLARA idiom):
    with G > sample, medoids fit on a stride subsample and the fleet is
    assigned by JS-to-medoid — same partition as the dense fit on
    separated groups; G <= sample stays the exact dense path; the
    signature only changes when the knob leaves its default (so
    pre-fit_sample checkpoints keep resuming)."""
    g = 60
    means = np.zeros((g, 3), np.float32)
    means[g // 2:] += 25.0
    covs = np.tile(0.5 * np.eye(3, dtype=np.float32), (g, 1, 1))
    means += rng.normal(scale=0.1, size=means.shape).astype(np.float32)
    dense = fit_assignments(means, covs, k=2)
    sub = fit_assignments(means, covs, k=2, sample=16)
    # identical partition up to label permutation
    agree = (sub.assignment == dense.assignment).mean()
    assert agree in (0.0, 1.0), agree
    assert len(set(sub.assignment[: g // 2])) == 1
    assert sub.assignment[0] != sub.assignment[-1]
    # sample >= G is the dense path, bitwise
    same = fit_assignments(means, covs, k=2, sample=g)
    assert np.array_equal(same.assignment, dense.assignment)
    assert ClusterSpec().signature() == ClusterSpec(
        fit_sample=4096).signature()
    assert ClusterSpec(fit_sample=512).signature() != \
        ClusterSpec().signature()
    with pytest.raises(ValueError, match="fit_sample"):
        ClusterSpec(fit_sample=-1)


def test_assignment_padding_invariance():
    """PARITY §8 for clusters: the same fleet padded to a wider client
    axis fits the IDENTICAL assignment — absolute gateway ids, mask-
    weighted probe (pad rows carry exact-zero weight)."""
    cfg = build_cfg()
    data = build_data(cfg)
    data_pad = build_data(cfg, pad_to=8)
    eng = build_engine(cfg, data, cluster=ClusterSpec(k=2))
    eng_pad = build_engine(cfg, data_pad, cluster=ClusterSpec(k=2))
    eng._ensure_cluster_fit(0)
    eng_pad._ensure_cluster_fit(0)
    assert np.array_equal(eng.cluster_assignment,
                          eng_pad.cluster_assignment)


# ----------------------------------------------------- K=1 bitwise pin ----

def test_k1_null_spec_bitwise_identical():
    """ClusterSpec(k=1) lowers to the exact pre-cluster program: final
    states AND the per-round artifact stream are bit-identical to an
    engine built without a spec (same executable by construction)."""
    cfg = build_cfg(num_rounds=3)
    data = build_data(cfg)
    plain = build_engine(cfg, data)
    null = build_engine(cfg, data, cluster=ClusterSpec(k=1))
    r_plain, _, _ = plain.run_schedule_chunk(0, 3)
    r_null, _, _ = null.run_schedule_chunk(0, 3)
    for a, b in zip(jax.tree.leaves(plain.states),
                    jax.tree.leaves(null.states)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    for ra, rb in zip(r_plain, r_null):
        assert ra.aggregator == rb.aggregator
        assert np.array_equal(ra.client_metrics, rb.client_metrics,
                              equal_nan=True)
        assert np.array_equal(ra.min_valid, rb.min_valid, equal_nan=True)
        if ra.agg_weights is not None:
            assert np.array_equal(ra.agg_weights, rb.agg_weights)
    assert null.cluster_assignment is None  # the null spec never fits


# ----------------------------------------- per-cluster merge scoping ----

def _cluster_rows_equal(tree, idx):
    """True iff all rows `idx` of every leaf are identical."""
    for leaf in jax.tree.leaves(tree):
        rows = np.asarray(leaf)[idx]
        if not np.allclose(rows, rows[0], rtol=0, atol=0):
            return False
    return True


def test_per_cluster_verification_and_broadcast_scoping():
    """After an accepted full-participation round at K=2, every client
    holds exactly ITS cluster's merge: rows agree within a cluster and
    differ across clusters — cluster B's params never bleed into A."""
    cfg = build_cfg(num_rounds=1, num_participants=1.0)
    data = build_data(cfg)
    eng = build_engine(cfg, data, cluster=ClusterSpec(k=2))
    res = eng.run_round_fused(0)
    assert res.aggregator is not None
    a = eng.cluster_assignment
    assert len(set(a.tolist())) == 2
    params = eng.states.params
    for c in (0, 1):
        assert _cluster_rows_equal(params, np.flatnonzero(a == c))
    leaf0 = np.asarray(jax.tree.leaves(params)[0])
    assert not np.allclose(leaf0[np.flatnonzero(a == 0)[0]],
                           leaf0[np.flatnonzero(a == 1)[0]])
    # the winning voter's weights normalize WITHIN each cluster
    w = res.agg_weights[:N]
    for c in (0, 1):
        np.testing.assert_allclose(w[a == c].sum(), 1.0, rtol=1e-5)


def test_personalization_keeps_decoder_local():
    """personalize=True: encoders converge to the cluster merge, decoders
    stay per-gateway (the broadcast is cluster-encoder + own-decoder)."""
    cfg = build_cfg(num_rounds=1, num_participants=1.0)
    data = build_data(cfg)
    eng = build_engine(cfg, data,
                       cluster=ClusterSpec(k=2, personalize=True))
    res = eng.run_round_fused(0)
    assert res.aggregator is not None
    a = eng.cluster_assignment
    params = eng.states.params
    for c in (0, 1):
        idx = np.flatnonzero(a == c)
        assert _cluster_rows_equal(params["encoder"], idx)
        if len(idx) > 1:  # decoders must NOT have merged
            leaf = np.asarray(jax.tree.leaves(params["decoder"])[0])
            assert not np.allclose(leaf[idx[0]], leaf[idx[1]])


def test_personalized_broadcast_helper():
    agg = {"encoder": {"w": np.ones((4, 3))}, "decoder": {"w": np.full((4, 3), 2.0)}}
    local = {"encoder": {"w": np.zeros((4, 3))}, "decoder": {"w": np.zeros((4, 3))}}
    out = personalized_broadcast(agg, local, ("encoder",))
    assert (np.asarray(out["encoder"]["w"]) == 1.0).all()
    assert (np.asarray(out["decoder"]["w"]) == 0.0).all()
    with pytest.raises(ValueError, match="not in the param tree"):
        personalized_broadcast(agg, local, ("head",))


# --------------------------------------------- elastic join inheritance ----

def test_elastic_join_recycles_from_nearest_cluster():
    """A joining slot inherits ITS cluster's incumbent mean, not the
    fleet mean: drive the fused body with a crafted membership slice (no
    election possible, so nothing else moves the joiner's params)."""
    from fedmse_tpu.federation.elastic import MembershipMasks

    cfg = build_cfg(num_rounds=2)
    data = build_data(cfg)
    eng = build_engine(cfg, data, cluster=ClusterSpec(k=2),
                       elastic=ElasticSpec(leave_p=0.0, join_p=0.0))
    eng._ensure_cluster_fit(0)
    a = eng.cluster_assignment
    joiner = int(np.flatnonzero(a == a[0])[1])  # a peer of client 0
    pre = jax.tree.map(lambda t: np.asarray(t).copy(), eng.states.params)

    member = np.ones(N, np.float32)
    joined = np.zeros(N, np.float32)
    joined[joiner] = 1.0
    masks = MembershipMasks(
        member=jax.numpy.asarray(member), joined=jax.numpy.asarray(joined),
        left=jax.numpy.asarray(np.zeros(N, np.float32)),
        generation=jax.numpy.asarray(joined.astype(np.int32)))
    eng._build_fused()
    sel = [int(np.flatnonzero(a != a[joiner])[0])]  # lone voter, no cand
    sel_idx, sel_mask = eng._selection_arrays(sel)
    states, _, out = eng._fused_round(
        eng.states, eng.data, eng._ver_x, eng._ver_m,
        jax.numpy.asarray(sel_idx), jax.numpy.asarray(sel_mask),
        eng._agg_count_padded(), jax.random.key(0),
        jax.numpy.asarray(0, jax.numpy.int32), elastic_in=masks,
        **eng._cluster_kwargs(0))
    assert int(out.aggregator) < 0  # nothing broadcast this round
    # the joiner's params == the mean of its cluster's OTHER members'
    # pre-round params (it joined, so it is not its own incumbent)
    own = np.flatnonzero((a == a[joiner])
                         & (np.arange(N) != joiner))
    got = jax.tree.leaves(states.params)
    want = jax.tree.leaves(pre)
    fleet_differs = False
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g)[joiner],
                                   w[own].mean(axis=0), rtol=2e-5,
                                   atol=1e-6)
        # ... and NOT the fleet mean (the clusters hold distinct inits);
        # zero-init bias leaves are trivially equal, so the contrast
        # only needs SOME leaf (the kernels) to differ
        fleet = np.delete(w, joiner, axis=0).mean(axis=0)
        if not np.allclose(np.asarray(g)[joiner], fleet, rtol=1e-4,
                           atol=1e-7):
            fleet_differs = True
    assert fleet_differs


# ----------------------------------------------------------- serving ----

def test_serving_cluster_routing_parity_and_zero_retrace():
    """cluster_models gathers [K, ...] cluster trees into the stacked
    per-gateway layout: scores match a per-cluster oracle, the swap that
    installs them is zero-retrace (_cache_size pin), and the roster
    carries the cluster column."""
    from fedmse_tpu.models import init_stacked_params
    from fedmse_tpu.serving import ServingEngine, ServingRoster

    rng = np.random.default_rng(0)
    model = make_model("autoencoder", DIM)
    params = init_stacked_params(model, jax.random.key(0), N)
    eng = ServingEngine.from_federation(model, "autoencoder", params,
                                        max_bucket=32)
    eng.warmup()
    cache = eng._score_fn._cache_size()
    rows = rng.normal(size=(24, DIM)).astype(np.float32)
    gws = (np.arange(24) % N).astype(np.int32)
    base = eng.score(rows, gws)

    # K=2 cluster models: gather per gateway, install as a hot swap with
    # the cluster column riding the roster
    assignment = np.asarray([0, 1, 0, 1, 0, 1], np.int32)
    cl_params = jax.tree.map(
        lambda t: np.stack([np.asarray(t)[0], np.asarray(t)[3]]), params)
    routed = cluster_models(cl_params, assignment)
    roster = ServingRoster(member=np.ones(N, bool),
                           generation=np.zeros(N, np.int64),
                           cluster=assignment)
    eng2 = ServingEngine.from_federation(model, "autoencoder", params,
                                         max_bucket=32, roster=roster)
    eng2.warmup()
    cache2 = eng2._score_fn._cache_size()
    eng2.swap_state(params=routed, roster=roster)
    got = eng2.score(rows, gws)
    assert eng2._score_fn._cache_size() == cache2  # zero retrace
    assert eng._score_fn._cache_size() == cache
    assert eng2.roster.cluster is not None

    # oracle: each row scored by its gateway's CLUSTER model directly
    for c in (0, 1):
        single = jax.tree.map(lambda t, c=c: np.asarray(t)[c][None],
                              cl_params)
        oracle = ServingEngine(model, "autoencoder", single,
                               multi_tenant=True, max_bucket=32)
        sel = np.flatnonzero(assignment[gws] == c)
        np.testing.assert_allclose(
            got[sel], oracle.score(rows[sel], np.zeros(len(sel), np.int32)),
            rtol=1e-5, atol=1e-6)
    # the swap changed what gateways serve (different cluster models)
    assert not np.allclose(base, got)

    # roster validation: a mis-shaped cluster column fails loudly
    with pytest.raises(ValueError, match="cluster column"):
        ServingRoster(member=np.ones(N, bool),
                      generation=np.zeros(N, np.int64),
                      cluster=np.zeros(N + 1, np.int32))


# ------------------------------------------------------- checkpointing ----

def test_checkpoint_roundtrip_and_k_change_error(tmp_path):
    """The assignment rides the checkpoint extra: a resume re-pins it
    (bit-identical continuation), and a K change fails with a message
    naming the cluster mismatch, not an Orbax tree error."""
    from fedmse_tpu.checkpointing import CheckpointManager

    cfg = build_cfg(num_rounds=2, fused_schedule_chunk=1)
    data = build_data(cfg)
    spec = ClusterSpec(k=2)
    eng = build_engine(cfg, data, cluster=spec)
    mgr = CheckpointManager(str(tmp_path / "ck"))
    eng.run_round_fused(0)
    extra = {"cluster": spec.signature(), "cluster_k": spec.k,
             "cluster_assignment": eng.cluster_assignment.tolist(),
             "cluster_fitted_round": 0}
    mgr.save("t", eng.states, eng.host, 1, extra=extra)

    # round-trip: the recorded assignment validates and recovers
    vec = assignment_from_extra(mgr.extra("t"), spec, N)
    assert np.array_equal(vec, eng.cluster_assignment)

    # K change: clear mismatch error (the acceptance-named guard)
    with pytest.raises(ValueError, match="cluster_k=2"):
        assignment_from_extra(mgr.extra("t"), ClusterSpec(k=4), N)
    # ... and the signature guard in the restore path names cluster too
    eng4 = build_engine(cfg, data, cluster=ClusterSpec(k=4))
    with pytest.raises(ValueError, match="cluster"):
        mgr.restore("t", eng4.states,
                    expected_extra={"cluster": ClusterSpec(k=4).signature()},
                    extra_defaults={"cluster": None})

    # a pre-cluster snapshot (no cluster keys) simply re-fits
    mgr.save("old", eng.states, eng.host, 1, extra={})
    assert assignment_from_extra(mgr.extra("old"), spec, N) is None

    # pinning an out-of-range assignment fails eagerly
    with pytest.raises(ValueError, match="re-tenants"):
        eng.set_cluster_assignment(np.asarray([0, 1, 2, 0, 1, 2]))


def test_assignment_rides_engine_pin():
    """set_cluster_assignment pins: the engine never re-fits over it and
    the padded cluster_in vector reflects it."""
    cfg = build_cfg(num_rounds=1)
    data = build_data(cfg)
    eng = build_engine(cfg, data, cluster=ClusterSpec(k=2, refit_every=1))
    pin = np.asarray([1, 0, 1, 0, 1, 0], np.int32)
    eng.set_cluster_assignment(pin)
    eng.run_round_fused(0)
    assert np.array_equal(eng.cluster_assignment, pin)


# ---------------------------------------------------- stats plumbing ----

def test_latent_stats_masked_rows(rng):
    """The stats program honors the row mask: masked-out rows cannot move
    a gateway's latent mean/cov (the ragged-shard contract)."""
    model = make_model("autoencoder", DIM, 8, 4)
    from fedmse_tpu.models import init_client_params
    probe = init_client_params(model, jax.random.key(0))
    stats_fn = make_latent_stats_fn(model)
    x = rng.normal(size=(2, 40, DIM)).astype(np.float32)
    m = np.ones((2, 40), np.float32)
    m[:, 30:] = 0.0
    x_junk = x.copy()
    x_junk[:, 30:] = 1e6  # garbage in the masked tail
    mean_a, cov_a = stats_fn(probe, x, m)
    mean_b, cov_b = stats_fn(probe, x_junk, m)
    np.testing.assert_allclose(np.asarray(mean_a), np.asarray(mean_b))
    np.testing.assert_allclose(np.asarray(cov_a), np.asarray(cov_b))


def test_cli_cluster_end_to_end(tmp_path_factory, tmp_path):
    """Driver wiring: --cluster-k runs, tags its artifact tree, records
    the assignment in resume checkpoints, resumes under it, and refuses
    a K change with the clear cluster message."""
    import json

    from fedmse_tpu.config import DatasetConfig
    from fedmse_tpu.main import main as cli_main
    from tests.test_data import _write_client_csvs

    root = tmp_path_factory.mktemp("cluster_shards")
    _write_client_csvs(str(root), N, dim=DIM, n_normal=80, n_abnormal=30)
    cfg_path = root / "config.json"
    with open(cfg_path, "w") as f:
        json.dump(DatasetConfig.for_client_dirs(str(root), N).to_json(), f)

    def cli(extra):
        return cli_main([
            "--dataset-config", str(cfg_path),
            "--model-types", "hybrid", "--update-types", "avg",
            "--network-size", str(N), "--dim-features", str(DIM),
            "--epochs", "1", "--batch-size", "8", "--no-save",
            "--global-patience", "99", "--fused-schedule-chunk", "2",
            "--checkpoint-dir", str(tmp_path / "c"),
            "--experiment-name", "cl",
            "--resume-dir", str(tmp_path / "r")] + extra)

    out = cli(["--cluster-k", "2", "--num-rounds", "2"])
    assert out["cluster"]["k"] == 2
    import glob
    host_files = glob.glob(str(tmp_path / "r" / "*.host.json"))
    assert len(host_files) == 1
    extra = json.load(open(host_files[0]))["extra"]
    assert extra["cluster_k"] == 2
    assert len(extra["cluster_assignment"]) == N

    # resume continues (round 3 only) under the recorded assignment
    out = cli(["--cluster-k", "2", "--num-rounds", "3"])
    assert len(out["results"]["hybrid/avg/run0"]["round_times"]) == 1

    # a K change refuses with the cluster-naming message
    with pytest.raises(ValueError, match="cluster"):
        cli(["--cluster-k", "4", "--num-rounds", "4"])


def test_cluster_assignment_extra_roundtrip(rng):
    means, covs = _rand_gaussians(rng, N, 4)
    fit = fit_assignments(means, covs, k=3, fitted_round=5)
    extra = fit.to_extra()
    assert extra["cluster_k"] == 3
    back = ClusterAssignment.from_arrays(3, np.asarray(
        extra["cluster_assignment"], np.int32), means, covs,
        fitted_round=extra["cluster_fitted_round"])
    assert np.array_equal(back.assignment, fit.assignment)
    assert back.fitted_round == 5
    assert fit.padded(10).shape == (10,)
    assert (fit.padded(10)[N:] == 0).all()
