"""Attack simulation (federation/attack.py) exercising the verification
subsystem end-to-end: poisoned aggregated models must be rejected by the
param-delta / performance checks (reference model_verifier.py:72-75), the
rejected counter must grow toward the 'possible attack' threshold
(client_trainer.py:201-203), and honest training must be unaffected."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fedmse_tpu.config import CompatConfig, ExperimentConfig
from fedmse_tpu.data import build_dev_dataset, stack_clients, synthetic_clients
from fedmse_tpu.federation import AttackSpec, RoundEngine, make_poison_fn, poison_params
from fedmse_tpu.models import make_model, init_client_params
from fedmse_tpu.utils.seeding import ExperimentRngs

DIM = 12
N = 4


def build_engine(poison_fn=None, fused=True, **cfg_kw):
    cfg = ExperimentConfig(
        dim_features=DIM, network_size=N, epochs=2, batch_size=8,
        compat=CompatConfig(vote_tie_break=False), **cfg_kw)
    clients = synthetic_clients(n_clients=N, dim=DIM, n_normal=120,
                                n_abnormal=60)
    rngs = ExperimentRngs(run=0)
    dev_x = build_dev_dataset(clients, rngs.data_rng)
    data = stack_clients(clients, dev_x, cfg.batch_size)
    m = make_model("hybrid", DIM, shrink_lambda=cfg.shrink_lambda)
    return RoundEngine(m, cfg, data, n_real=N, rngs=rngs, model_type="hybrid",
                       update_type="avg", fused=fused, poison_fn=poison_fn)


def test_poison_params_shapes_and_kinds():
    m = make_model("hybrid", DIM, shrink_lambda=1.0)
    params = init_client_params(m, jax.random.key(0))
    for kind in ("scale", "noise", "sign_flip", "zero"):
        out = poison_params(params, AttackSpec(kind=kind, strength=3.0),
                            jax.random.key(1))
        assert jax.tree.structure(out) == jax.tree.structure(params)
    zero = poison_params(params, AttackSpec(kind="zero"), jax.random.key(1))
    assert all(float(jnp.abs(t).max()) == 0.0 for t in jax.tree.leaves(zero))
    scaled = poison_params(params, AttackSpec(kind="scale", strength=2.0),
                           jax.random.key(1))
    np.testing.assert_allclose(np.asarray(jax.tree.leaves(scaled)[0]),
                               2.0 * np.asarray(jax.tree.leaves(params)[0]),
                               rtol=1e-6)


def test_unknown_kind_rejected():
    with pytest.raises(ValueError):
        AttackSpec(kind="meteor")


@pytest.mark.parametrize("fused", [True, False])
def test_scale_attack_rejected_after_first_contact(fused):
    """Round 0's update is accepted unconditionally (first-contact rule,
    model_verifier.py:41-47); attacked later rounds must be rejected and the
    rejected counters must grow."""
    spec = AttackSpec(kind="scale", strength=50.0, start_round=1)
    eng = build_engine(poison_fn=make_poison_fn(spec), fused=fused)

    r0 = eng.run_round(0)  # honest? no — start_round=1, so round 0 is clean
    assert all(row["rejected_updates"] == 0 for row in r0.verification_results)

    rejected_counts = []
    for r in range(1, 4):
        res = eng.run_round(r)
        if res.aggregator is None:
            continue
        rejected_counts.append(
            max(row["rejected_updates"] for row in res.verification_results))
    # every attacked round adds a rejection for every receiving client
    assert rejected_counts and rejected_counts[-1] >= 2
    assert rejected_counts == sorted(rejected_counts)


def test_attack_blocked_models_keep_prior_params():
    """Rejected updates must leave the receivers' models untouched — EXCEPT
    clients receiving their first-ever update, which the reference accepts
    unconditionally (first-contact rule, model_verifier.py:41-47): those load
    even a poisoned broadcast. The round-0 aggregator is exactly such a
    client in round 1 (an aggregator's own history is never updated)."""
    spec = AttackSpec(kind="zero", start_round=1)
    eng = build_engine(poison_fn=make_poison_fn(spec))
    seen_before = None
    r0 = eng.run_round(0)
    seen_before = np.asarray(jax.device_get(eng.states.hist_seen)).copy()
    res = eng.run_round(1)
    assert res.aggregator is not None
    rejected = np.asarray(jax.device_get(eng.states.rejected))
    leaf = np.asarray(jax.tree.leaves(jax.device_get(eng.states.params))[0])
    for i in range(N):
        if i == res.aggregator:
            continue  # loads its own (poisoned) aggregate unconditionally
        if seen_before[i]:
            # verified receiver: rejects the zero model, keeps its params
            assert rejected[i] == 1
            assert np.abs(leaf[i]).max() > 0.0
        else:
            # first-contact receiver: the quirk accepts even a poisoned model
            assert rejected[i] == 0
            assert np.abs(leaf[i]).max() == 0.0


def test_honest_run_has_no_rejections():
    eng = build_engine(poison_fn=None)
    for r in range(3):
        res = eng.run_round(r)
    assert all(row["rejected_updates"] == 0
               for row in res.verification_results)


def test_attack_schedule_every_k():
    """every_k=2 attacks rounds 0,2,...; clean rounds re-accept (the verifier
    compares against the last RECEIVED state, so a clean broadcast after a
    huge poisoned one still fails the delta check — counters keep growing —
    while small-perturbation schedules recover; here we just pin the
    schedule logic itself."""
    spec = AttackSpec(kind="scale", strength=50.0, every_k=2, start_round=0)
    fn = make_poison_fn(spec)
    m = make_model("hybrid", DIM, shrink_lambda=1.0)
    params = init_client_params(m, jax.random.key(0))
    leaf0 = np.asarray(jax.tree.leaves(params)[0])
    out0 = fn(params, jnp.asarray(0), jax.random.key(1))
    out1 = fn(params, jnp.asarray(1), jax.random.key(1))
    np.testing.assert_allclose(np.asarray(jax.tree.leaves(out0)[0]),
                               50.0 * leaf0, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(jax.tree.leaves(out1)[0]),
                               leaf0, rtol=1e-6)


def test_every_k_zero_rejected():
    with pytest.raises(ValueError):
        AttackSpec(kind="scale", every_k=0)


def test_stop_round_bounds_the_attack_window():
    """stop_round makes the attack a transient burst: rounds in
    [start_round, stop_round) are attacked, everything after is clean —
    the schedule the chaos rounds-to-recover metric measures
    (fedmse_tpu/chaos/metrics.py)."""
    spec = AttackSpec(kind="scale", strength=50.0, start_round=1,
                      stop_round=3)
    fn = make_poison_fn(spec)
    m = make_model("hybrid", DIM, shrink_lambda=1.0)
    params = init_client_params(m, jax.random.key(0))
    leaf0 = np.asarray(jax.tree.leaves(params)[0])
    expected = {0: 1.0, 1: 50.0, 2: 50.0, 3: 1.0, 4: 1.0}
    for rnd, factor in expected.items():
        out = fn(params, jnp.asarray(rnd), jax.random.key(1))
        np.testing.assert_allclose(np.asarray(jax.tree.leaves(out)[0]),
                                   factor * leaf0, rtol=1e-6,
                                   err_msg=f"round {rnd}")


def test_stop_round_respects_every_k():
    """The burst window composes with the every_k cadence: start=0, k=2,
    stop=4 attacks rounds 0 and 2 only."""
    spec = AttackSpec(kind="scale", strength=50.0, every_k=2,
                      start_round=0, stop_round=4)
    fn = make_poison_fn(spec)
    m = make_model("hybrid", DIM, shrink_lambda=1.0)
    params = init_client_params(m, jax.random.key(0))
    leaf0 = np.asarray(jax.tree.leaves(params)[0])
    for rnd, factor in {0: 50.0, 1: 1.0, 2: 50.0, 3: 1.0,
                        4: 1.0, 6: 1.0}.items():
        out = fn(params, jnp.asarray(rnd), jax.random.key(1))
        np.testing.assert_allclose(np.asarray(jax.tree.leaves(out)[0]),
                                   factor * leaf0, rtol=1e-6,
                                   err_msg=f"round {rnd}")


def test_stop_round_validation():
    """An empty window would silently never attack — rejected eagerly,
    same idiom as every_k=0."""
    with pytest.raises(ValueError, match="stop_round"):
        AttackSpec(kind="scale", start_round=2, stop_round=2)
    with pytest.raises(ValueError, match="stop_round"):
        AttackSpec(kind="scale", start_round=5, stop_round=3)
    # a valid window constructs fine
    AttackSpec(kind="scale", start_round=2, stop_round=5)


def test_transient_attack_stop_round_threads_through_engine():
    """End-to-end gate on stop_round INSIDE the fused schedule (not just
    the poison_fn unit): a stop_round=3 burst and a never-stopping attack
    share the exact poison schedule through rounds 0-2, so their round
    streams are equal up to the stop — then they MUST diverge, because
    each round's aggregator loads its own aggregate unconditionally
    (client_trainer.py:333): the stopping run seats an honest aggregate,
    the other a 50x-scaled one. An engine path that silently dropped
    stop_round would keep the streams identical and fail this test.
    (No claim about counter RECOVERY is made: trashed ex-aggregators
    pollute later aggregates, so even honest post-burst broadcasts keep
    being rejected — the history-poisoning dynamic attack_sweep.py
    measures.)"""
    def run(stop_round):
        spec = AttackSpec(kind="scale", strength=50.0, start_round=1,
                          stop_round=stop_round)
        eng = build_engine(poison_fn=make_poison_fn(spec))
        return [eng.run_round(r) for r in range(6)], eng

    burst, beng = run(stop_round=3)
    forever, feng = run(stop_round=None)
    for ra, rb in zip(burst[:3], forever[:3]):  # identical through the burst
        assert ra.selected == rb.selected
        assert ra.aggregator == rb.aggregator
        np.testing.assert_allclose(ra.client_metrics, rb.client_metrics,
                                   rtol=1e-6, atol=0)
    post_aggregated = [r for r in range(3, 6)
                      if forever[r].aggregator is not None]
    assert post_aggregated  # the comparison needs a post-burst broadcast
    # divergence is asserted on the STATES, not the metric stream: each
    # post-burst aggregator seats an honest vs a 50x-scaled aggregate, so
    # the param trees must differ even when AUC saturates to the same
    # value on both trajectories
    assert any(
        not np.allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=0)
        for a, b in zip(jax.tree.leaves(beng.states.params),
                        jax.tree.leaves(feng.states.params))), \
        "stop_round had no effect on the schedule"
