"""Unit tests for loss math, metrics, stats — checked against torch/sklearn
references where available (the same libraries the reference implementation
uses, so agreement here is agreement with the reference's numerics)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fedmse_tpu.ops.losses import mse_loss, per_sample_mse, prox_term, shrink_loss
from fedmse_tpu.ops.metrics import classification_metrics, roc_auc
from fedmse_tpu.ops.stats import masked_mean_std, masked_percentile


def test_mse_loss_matches_torch(rng):
    import torch
    x = rng.normal(size=(13, 7)).astype(np.float32)
    y = rng.normal(size=(13, 7)).astype(np.float32)
    want = torch.nn.MSELoss(reduction="mean")(torch.tensor(x), torch.tensor(y)).item()
    got = float(mse_loss(jnp.asarray(x), jnp.asarray(y)))
    assert got == pytest.approx(want, rel=1e-6)


def test_mse_loss_masked_equals_unmasked_subset(rng):
    x = rng.normal(size=(10, 4)).astype(np.float32)
    y = rng.normal(size=(10, 4)).astype(np.float32)
    mask = np.array([1] * 6 + [0] * 4, dtype=np.float32)
    got = float(mse_loss(jnp.asarray(x), jnp.asarray(y), jnp.asarray(mask)))
    want = float(mse_loss(jnp.asarray(x[:6]), jnp.asarray(y[:6])))
    assert got == pytest.approx(want, rel=1e-6)


def test_shrink_loss_matches_reference_formula(rng):
    """MSE + λ·(Σ‖z‖₂)/rows (reference Shrink_Autoencoder.py:138-156)."""
    import torch
    x = rng.normal(size=(9, 5)).astype(np.float32)
    recon = rng.normal(size=(9, 5)).astype(np.float32)
    z = rng.normal(size=(9, 3)).astype(np.float32)
    lam = 5.0
    want = (torch.nn.MSELoss(reduction="mean")(torch.tensor(x), torch.tensor(recon))
            + lam * torch.sum(torch.linalg.vector_norm(torch.tensor(z), dim=1)) / 9).item()
    got = float(shrink_loss(jnp.asarray(x), jnp.asarray(recon), jnp.asarray(z), lam))
    assert got == pytest.approx(want, rel=1e-6)


def test_shrink_loss_grad_finite_with_zero_padded_rows():
    """A zero-PADDED row has an exactly-zero latent at init (zero biases),
    where the naive ‖·‖₂ gradient is NaN — and 0·NaN poisons the whole
    batch gradient. The safe-norm guard must keep gradients finite while
    leaving real-row values untouched (bit-identical to linalg.norm)."""
    from fedmse_tpu.models import make_model

    model = make_model("hybrid", 5, shrink_lambda=5.0)
    p = model.init(jax.random.key(0), jnp.zeros((1, 5)))["params"]
    x = jnp.array([[1., 2, 3, 4, 5], [0, 0, 0, 0, 0]])  # real + zero pad
    m = jnp.array([1., 0.])

    def loss(p):
        lat, rec = model.apply({"params": p}, x)
        return shrink_loss(x, rec, lat, 5.0, m)

    g = jax.grad(loss)(p)
    assert all(bool(jnp.isfinite(l).all()) for l in jax.tree.leaves(g))
    # real (nonzero-latent) rows: the PRODUCTION loss must equal the naive
    # linalg.norm formula bit-for-bit — exercise shrink_loss itself so a
    # future epsilon-style drift in losses.py fails here
    rng2 = np.random.default_rng(0)
    x2 = jnp.asarray(rng2.normal(size=(7, 5)), dtype=jnp.float32)
    r2 = jnp.asarray(rng2.normal(size=(7, 5)), dtype=jnp.float32)
    z2 = jnp.asarray(rng2.normal(size=(7, 3)), dtype=jnp.float32)
    want = (mse_loss(x2, r2)
            + 5.0 * jnp.mean(jnp.linalg.norm(z2, axis=-1)))
    assert float(shrink_loss(x2, r2, z2, 5.0)) == float(want)


def test_prox_term(rng):
    p = {"a": jnp.asarray(rng.normal(size=(3, 2)).astype(np.float32)),
         "b": jnp.asarray(rng.normal(size=(4,)).astype(np.float32))}
    g = jax.tree.map(lambda t: t + 0.5, p)
    want = sum(float(np.sum((np.asarray(a) - np.asarray(b)) ** 2))
               for a, b in zip(jax.tree.leaves(p), jax.tree.leaves(g)))
    assert float(prox_term(p, g)) == pytest.approx(want, rel=1e-6)


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_auc_matches_sklearn(seed):
    from sklearn.metrics import roc_auc_score
    rng = np.random.default_rng(seed)
    n = 257
    labels = (rng.random(n) < 0.3).astype(np.float32)
    scores = rng.normal(size=n).astype(np.float32)
    # inject ties
    scores[::5] = np.round(scores[::5], 1)
    want = roc_auc_score(labels, scores)
    got = float(roc_auc(jnp.asarray(labels), jnp.asarray(scores)))
    assert got == pytest.approx(want, abs=1e-6)


def test_auc_masked_matches_subset():
    from sklearn.metrics import roc_auc_score
    rng = np.random.default_rng(42)
    n, valid = 64, 40
    labels = (rng.random(n) < 0.5).astype(np.float32)
    scores = rng.normal(size=n).astype(np.float32)
    mask = (np.arange(n) < valid).astype(np.float32)
    want = roc_auc_score(labels[:valid], scores[:valid])
    got = float(roc_auc(jnp.asarray(labels), jnp.asarray(scores), jnp.asarray(mask)))
    assert got == pytest.approx(want, abs=1e-6)


def test_auc_large_scale_no_overflow():
    """Regression: int32 overflow at N-BaIoT scale (>=46341 rows per class)."""
    from sklearn.metrics import roc_auc_score
    rng = np.random.default_rng(9)
    n = 120_000
    labels = (rng.random(n) < 0.6).astype(np.float32)
    scores = (rng.normal(size=n) + labels).astype(np.float32)
    want = roc_auc_score(labels, scores)
    got = float(roc_auc(jnp.asarray(labels), jnp.asarray(scores)))
    assert got == pytest.approx(want, abs=1e-4)


def test_auc_single_class_is_nan():
    labels = jnp.zeros(10)
    scores = jnp.arange(10.0)
    assert np.isnan(float(roc_auc(labels, scores)))


def test_classification_metrics_match_sklearn():
    from sklearn.metrics import f1_score, precision_score, recall_score
    rng = np.random.default_rng(3)
    labels = (rng.random(100) < 0.4).astype(np.float32)
    scores = rng.random(100).astype(np.float32)
    pred = (scores > 0.5).astype(int)
    f1, prec, rec = classification_metrics(jnp.asarray(labels), jnp.asarray(scores))
    assert float(f1) == pytest.approx(f1_score(labels, pred), abs=1e-6)
    assert float(prec) == pytest.approx(precision_score(labels, pred), abs=1e-6)
    assert float(rec) == pytest.approx(recall_score(labels, pred), abs=1e-6)


def test_masked_mean_std_ddof(rng):
    x = rng.normal(size=(20, 3)).astype(np.float32)
    mask = (np.arange(20) < 12).astype(np.float32)
    mean0, std0 = masked_mean_std(jnp.asarray(x), jnp.asarray(mask), ddof=0)
    mean1, std1 = masked_mean_std(jnp.asarray(x), jnp.asarray(mask), ddof=1)
    np.testing.assert_allclose(np.asarray(mean0), x[:12].mean(0), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(std0), x[:12].std(0), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(std1), x[:12].std(0, ddof=1), rtol=1e-5)


@pytest.mark.parametrize("q", [0.0, 25.0, 50.0, 95.0, 100.0])
def test_masked_percentile_matches_numpy(q):
    rng = np.random.default_rng(7)
    vals = rng.normal(size=33).astype(np.float32)
    mask = (np.arange(33) < 21).astype(np.float32)
    want = np.percentile(vals[:21], q)
    got = float(masked_percentile(jnp.asarray(vals), q, jnp.asarray(mask)))
    assert got == pytest.approx(want, abs=1e-5)


def test_centroid_matches_sklearn_reference(rng):
    """Full parity with reference Centroid.py fit/get_density/predict."""
    from sklearn import preprocessing
    import scipy.spatial
    from fedmse_tpu.models.centroid import fit_centroid

    train = rng.normal(size=(50, 7)).astype(np.float32)
    test = rng.normal(size=(30, 7)).astype(np.float32)

    scaler = preprocessing.StandardScaler().fit(train)
    dists_ref = scipy.spatial.distance.cdist(
        scaler.transform(test), np.zeros((1, 7))).mean(axis=1)
    thr_ref = np.percentile(scipy.spatial.distance.cdist(
        scaler.transform(train), np.zeros((1, 7))).mean(axis=1), 50.0)

    cen = fit_centroid(jnp.asarray(train))
    got = np.asarray(cen.get_density(jnp.asarray(test)))
    np.testing.assert_allclose(got, dists_ref, rtol=1e-4)
    assert float(cen.abs_threshold) == pytest.approx(thr_ref, rel=1e-4)
    np.testing.assert_array_equal(
        np.asarray(cen.predict(jnp.asarray(test))), dists_ref > thr_ref)
