"""Measured autotuner + tuning cache (fedmse_tpu/tune/, DESIGN.md §24):
exact-signature invalidation (a stale entry is INVISIBLE and provably
re-measures — the r20 acceptance criterion), FEDMSE_TUNE-gated disk
writes (un-gated stores never dirty the committed TUNE_CACHE.json),
min-over-k argmin with the full audit table, the ladder helpers, the
serving engine's tuned/explicit ladder path (scores identical to pow2 —
the ladder changes padding, never math), the pallas block_rows
tune→lookup round trip, and plan_merge's cached re-plan skip."""

import json
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fedmse_tpu.models import make_model
from fedmse_tpu.models.autoencoder import init_client_params
from fedmse_tpu.parallel.costmodel import plan_merge
from fedmse_tpu.serving.engine import ServingEngine
from fedmse_tpu.tune import TuningCache, measure_candidates, sites
from fedmse_tpu.tune.cache import default_cache

pytestmark = pytest.mark.tune

DIM = 115


# ------------------------------ cache ------------------------------------ #

def test_cache_roundtrip_exact_signature(tmp_path):
    path = tmp_path / "tc.json"
    c = TuningCache(path, writable=True)
    sig = {"backend": "cpu", "probe": 8, "candidates": [1, 2]}
    c.store("site", sig, 42, wall_s=0.5)
    assert c.lookup("site", sig)["choice"] == 42
    # signature equality is over the JSON image: key order and tuple vs
    # list must not matter...
    reordered = {"candidates": (1, 2), "probe": 8, "backend": "cpu"}
    assert c.lookup("site", reordered)["choice"] == 42
    # ...but ANY value drift makes the entry invisible
    assert c.lookup("site", {**sig, "probe": 9}) is None
    assert c.lookup("site", {**sig, "candidates": [1, 2, 4]}) is None
    assert c.lookup("other_site", sig) is None
    # a fresh reader sees the atomic write
    assert TuningCache(path).lookup("site", sig)["choice"] == 42
    on_disk = json.loads(path.read_text())
    assert on_disk["version"] == 1 and "site" in on_disk["sites"]


def test_stale_signature_provably_remeasures(tmp_path):
    """Acceptance: a cache entry with a mismatched signature re-measures."""
    c = TuningCache(tmp_path / "tc.json", writable=True)
    calls = []

    def measure():
        calls.append(1)
        return {"choice": 10 * len(calls), "wall_s": 0.1}

    sig_a = {"backend": "cpu", "candidates": [1, 2]}
    e1 = c.get_or_measure("s", sig_a, measure)
    assert (e1["choice"], e1["cached"], len(calls)) == (10, False, 1)
    e2 = c.get_or_measure("s", sig_a, measure)
    assert (e2["choice"], e2["cached"], len(calls)) == (10, True, 1)
    # changed candidate grid = stale signature -> measured AGAIN
    sig_b = {"backend": "cpu", "candidates": [1, 2, 3]}
    e3 = c.get_or_measure("s", sig_b, measure)
    assert (e3["choice"], e3["cached"], len(calls)) == (20, False, 2)
    # both entries coexist; re-storing sig_a REPLACES, never duplicates
    c.store("s", sig_a, 99)
    rows = json.loads((tmp_path / "tc.json").read_text())["sites"]["s"]
    assert len(rows) == 2
    assert c.lookup("s", sig_a)["choice"] == 99


def test_writes_are_env_gated(tmp_path, monkeypatch):
    monkeypatch.delenv("FEDMSE_TUNE", raising=False)
    path = tmp_path / "tc.json"
    c = TuningCache(path)  # writable=None -> FEDMSE_TUNE gate
    c.store("s", {"a": 1}, 7)
    assert not path.exists()                   # committed artifact untouched
    assert c.lookup("s", {"a": 1})["choice"] == 7   # but the session reuses it
    monkeypatch.setenv("FEDMSE_TUNE", "1")
    c.store("s", {"a": 2}, 8)
    data = json.loads(path.read_text())        # gated write flushes BOTH
    sigs = [e["signature"] for e in data["sites"]["s"]]
    assert {"a": 1} in sigs and {"a": 2} in sigs


def test_corrupt_cache_reads_as_empty(tmp_path):
    path = tmp_path / "tc.json"
    path.write_text("{not json")
    c = TuningCache(path, writable=True)
    assert c.lookup("s", {"a": 1}) is None
    c.store("s", {"a": 1}, 5)                  # and store repairs the file
    assert TuningCache(path).lookup("s", {"a": 1})["choice"] == 5


def test_measure_candidates_argmin_and_table():
    def run(delay):
        time.sleep(delay)
        return delay

    out = measure_candidates([0.004, 0.0, 0.002], run, repeats=1)
    assert out["choice"] == 0.0
    assert [r["value"] for r in out["candidates"]] == [0.004, 0.0, 0.002]
    assert all(r["wall_s"] >= 0.0 for r in out["candidates"])
    assert out["wall_s"] == min(r["wall_s"] for r in out["candidates"])


# ------------------------------ ladders ----------------------------------- #

def test_ladder_helpers():
    assert sites.pow2_ladder(16) == [1, 2, 4, 8, 16]
    lc = sites.ladder_candidates(16)
    assert lc["pow2"] == [1, 2, 4, 8, 16]
    assert lc["pow2_mid"] == [1, 2, 3, 4, 6, 8, 12, 16]
    assert sites.ladder_bucket_for(5, lc["pow2"]) == 8
    assert sites.ladder_bucket_for(5, lc["pow2_mid"]) == 6   # padding 8->6
    assert sites.ladder_bucket_for(0, lc["pow2_mid"]) == 1
    assert sites.ladder_bucket_for(16, lc["pow2_mid"]) == 16
    with pytest.raises(ValueError):
        sites.ladder_bucket_for(17, lc["pow2"])


def _single_engine(**kw):
    model = make_model("autoencoder", DIM)
    params = init_client_params(model, jax.random.PRNGKey(0))
    return model, ServingEngine(model, "autoencoder", params, None,
                                multi_tenant=False, max_bucket=16, **kw)


def test_engine_explicit_ladder_same_scores_less_padding():
    _, e_mid = _single_engine(bucket_ladder=[1, 2, 3, 4, 6, 8, 12, 16])
    _, e_p2 = _single_engine(bucket_ladder="pow2")
    assert e_mid.buckets == [1, 2, 3, 4, 6, 8, 12, 16]
    assert e_p2.buckets == [1, 2, 4, 8, 16]
    assert (e_mid.bucket_for(5), e_p2.bucket_for(5)) == (6, 8)
    rows = np.asarray(np.random.default_rng(0).normal(size=(5, DIM)),
                      np.float32)
    # the ladder changes PADDING only: scores are the same numbers
    np.testing.assert_allclose(e_mid.score(rows), e_p2.score(rows),
                               rtol=1e-5, atol=1e-6)
    with pytest.raises(ValueError):
        _single_engine(bucket_ladder=[1, 2, 3])       # last rung != max_bucket
    with pytest.raises(ValueError):
        _single_engine(bucket_ladder=[0, 2, 16])      # non-positive rung
    # no 1-rung is legal: a 1-row request just pads to the first rung
    _, e_no1 = _single_engine(bucket_ladder=[2, 4, 16])
    assert e_no1.bucket_for(1) == 2


def test_engine_auto_ladder_reads_cache_keyed_on_max_bucket(
        tmp_path, monkeypatch):
    monkeypatch.setenv("FEDMSE_TUNE_CACHE", str(tmp_path / "tc.json"))
    monkeypatch.setenv("FEDMSE_TUNE", "1")
    tuned = [1, 2, 3, 4, 6, 8, 12, 16]
    default_cache().store("serve_bucket_ladder",
                          sites._serve_signature(16, DIM), tuned,
                          ladder_name="pow2_mid")
    _, eng = _single_engine(bucket_ladder="auto")
    assert eng.buckets == tuned
    assert sites.lookup_serve_ladder(16, DIM) == tuned
    # an engine at another max_bucket misses the entry -> pow2 fallback
    model = make_model("autoencoder", DIM)
    params = init_client_params(model, jax.random.PRNGKey(0))
    eng8 = ServingEngine(model, "autoencoder", params, None,
                         multi_tenant=False, max_bucket=8)
    assert eng8.buckets == [1, 2, 4, 8]
    assert sites.lookup_serve_ladder(8, DIM) is None


# ------------------------- block_rows round trip -------------------------- #

def test_tune_block_rows_roundtrip(tmp_path, monkeypatch):
    """tune -> store -> lookup under one signature; drifting the probe
    makes the entry invisible again (pure-read lookup never measures)."""
    monkeypatch.setattr(sites, "_BLOCK_PROBE_ROWS", 64)
    cache = TuningCache(tmp_path / "tc.json", writable=True)
    assert sites.lookup_block_rows(cache) is None
    entry = sites.tune_block_rows(cache, repeats=1, probe_rows=64)
    assert entry["choice"] in sites.BLOCK_ROWS_CANDIDATES
    assert len(entry["candidates"]) == len(sites.BLOCK_ROWS_CANDIDATES)
    assert sites.lookup_block_rows(cache) == entry["choice"]
    monkeypatch.setattr(sites, "_BLOCK_PROBE_ROWS", 128)   # probe drift
    assert sites.lookup_block_rows(cache) is None


# ------------------------- plan_merge cache skip -------------------------- #

def test_plan_merge_remeasure_skip(mesh8, tmp_path, monkeypatch):
    """An identical plan_merge call hits the 'merge_plan' entry and skips
    the measured search; ANY argument drift re-measures."""
    monkeypatch.setenv("FEDMSE_TUNE_CACHE", str(tmp_path / "tc.json"))
    monkeypatch.setenv("FEDMSE_TUNE", "1")
    kw = dict(k=2, block_sizes=(64,), repeats=1, max_group_candidates=1)
    p1 = plan_merge(mesh8, [64], **kw)
    assert p1["cached"] is False
    p2 = plan_merge(mesh8, [64], **kw)
    assert p2["cached"] is True
    assert p2["chosen"] == p1["chosen"]
    assert p2["candidates"] == p1["candidates"]   # full audit table survives
    p3 = plan_merge(mesh8, [64], **{**kw, "dcn_gbps": 50.0})
    assert p3["cached"] is False
