"""Cohort-compacted, host-tiered client state (federation/tiered.py,
state.TieredClientStore; DESIGN.md §16).

Pins, in dependency order:
  * the tier's chunked init is bitwise the dense init, row by row;
  * bit-parity to the dense program at full participation (C == N): the
    tiered executor shares the dense engine's jitted round body, so
    states, per-round results AND the on-disk artifacts byte-match;
  * the prefetched double-buffered loop (stale-row patch included) is
    bit-identical to the serial per-round tiered path — the patch can
    never leak a stale row;
  * cohort gather/scatter is keyed to ABSOLUTE client ids (PARITY.md §8):
    growing the padded client axis re-tenants nothing;
  * memory accounting: device-resident bytes scale with the cohort width
    C, not N — and a 100k-client init never materializes a dense
    [N, ...] device tree (params or Adam moments);
  * checkpoints are layout-interchangeable (dense snapshot -> tier,
    tiered snapshot -> dense engine);
  * chaos / elastic / mesh-sharded slabs compose at cohort width.
"""

import glob
import os

import numpy as np
import pytest

import jax
import optax

from fedmse_tpu.config import ExperimentConfig
from fedmse_tpu.data import build_dev_dataset, stack_clients, synthetic_clients
from fedmse_tpu.data.stacking import pad_federated_data
from fedmse_tpu.federation import (ElasticSpec, RoundEngine, TieredClientStore,
                                   TieredRoundEngine, init_client_states)
from fedmse_tpu.chaos import ChaosSpec
from fedmse_tpu.models import make_model
from fedmse_tpu.utils.seeding import ExperimentRngs

pytestmark = pytest.mark.cohort

DIM, HID, LAT = 8, 6, 3


def _cfg(**kw):
    base = dict(num_participants=0.5, num_rounds=3, epochs=2,
                dim_features=DIM, hidden_neus=HID, latent_dim=LAT,
                state_layout="tiered")
    base.update(kw)
    return ExperimentConfig(**base)


def _federation(n=6, seed_cfg=None):
    cfg = seed_cfg or _cfg()
    rngs = ExperimentRngs(run=0, data_seed=cfg.data_seed)
    clients = synthetic_clients(n_clients=n, dim=DIM, n_normal=60,
                                n_abnormal=60)
    dev_x = build_dev_dataset(clients, rngs.data_rng)
    data = stack_clients(clients, dev_x, cfg.batch_size)
    return clients, data


def _model(cfg):
    return make_model("hybrid", DIM, HID, LAT, cfg.shrink_lambda)


def _tiered(cfg, data, n, **kw):
    return TieredRoundEngine(
        _model(cfg), cfg, data, n_real=n,
        rngs=ExperimentRngs(run=0, data_seed=cfg.data_seed),
        model_type="hybrid", update_type="mse_avg", **kw)


def _run(engine, rounds):
    out = []
    engine.run_rounds(0, rounds, lambda r, s: out.append(r) or False)
    return out


def _assert_states_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# --------------------------- init parity ------------------------------- #

def test_tier_init_rows_bitwise_match_dense_init():
    cfg = _cfg()
    model = _model(cfg)
    tx = optax.adam(cfg.lr_rate)
    key = jax.random.key(7)
    dense = jax.device_get(init_client_states(model, tx, key, 11))
    # chunk smaller than N so the chunked path (incl. the padded tail
    # dispatch) is actually exercised
    tier = TieredClientStore.create(model, tx, key, 11, init_chunk=4)
    _assert_states_equal(dense, tier.host)


# ------------------- bit-parity at full participation ------------------ #

def test_bit_parity_to_dense_at_full_participation(tmp_path):
    """C == N: same executable, same inputs — states, round results and
    on-disk artifacts are bit-identical to the dense program (the
    acceptance pin; compact_cohort=False puts the dense engine on the
    exact program the cohort executor compiles)."""
    from fedmse_tpu.checkpointing import ResultsWriter
    from fedmse_tpu.main import run_combination

    cfg_t = _cfg(num_participants=1.0, compact_cohort=False, num_rounds=3)
    cfg_d = cfg_t.replace(state_layout="dense", fused_pipeline=False)
    clients, data = _federation(6, cfg_t)
    names = [c.name for c in clients]

    outs, writers = {}, {}
    for tag, cfg in (("dense", cfg_d), ("tiered", cfg_t)):
        writers[tag] = ResultsWriter(str(tmp_path / tag), 6, "exp", "scen",
                                     "AUC", cfg.num_participants)
        outs[tag] = run_combination(cfg, data, 6, "hybrid", "mse_avg", 0,
                                    writer=writers[tag], device_names=names,
                                    save_checkpoints=True)
    np.testing.assert_array_equal(outs["dense"]["final_metrics"],
                                  outs["tiered"]["final_metrics"])
    assert outs["dense"]["aggregation_count"] == \
        outs["tiered"]["aggregation_count"]
    # artifact trees byte-compare (round JSON lines, verification rows,
    # per-client model.npz + tracking)
    d_files = sorted(glob.glob(str(tmp_path / "dense" / "**" / "*.*"),
                               recursive=True))
    t_files = sorted(glob.glob(str(tmp_path / "tiered" / "**" / "*.*"),
                               recursive=True))
    rel = [os.path.relpath(f, tmp_path / "dense") for f in d_files]
    assert rel == [os.path.relpath(f, tmp_path / "tiered") for f in t_files]
    assert rel  # non-empty artifact tree
    for df, tf in zip(d_files, t_files):
        with open(df, "rb") as f1, open(tf, "rb") as f2:
            assert f1.read() == f2.read(), f"artifact differs: {df}"


def test_partial_cohort_semantics_and_dense_agreement_on_cohort():
    """C < N: cohort clients' training outputs match the dense program's
    for the same selections (same per-lane math), and non-cohort clients
    read NaN metrics ('not measured this round')."""
    cfg_t = _cfg(num_participants=0.5, compact_cohort=False, num_rounds=1)
    cfg_d = cfg_t.replace(state_layout="dense")
    clients, data = _federation(6, cfg_t)
    tier = _tiered(cfg_t, data, 6)
    dense = RoundEngine(_model(cfg_d), cfg_d, data, n_real=6,
                        rngs=ExperimentRngs(run=0, data_seed=cfg_d.data_seed),
                        model_type="hybrid", update_type="mse_avg",
                        fused=True)
    rt = _run(tier, 1)[0]
    rd = dense.run_round_fused(0)
    assert rt.selected == rd.selected and rt.aggregator == rd.aggregator
    sel = np.asarray(rt.selected)
    # training curves are cohort-only in BOTH layouts — identical values
    np.testing.assert_array_equal(rt.min_valid[sel], rd.min_valid[sel])
    np.testing.assert_array_equal(rt.tracking[sel], rd.tracking[sel])
    off = np.setdiff1d(np.arange(6), sel)
    assert np.isnan(rt.client_metrics[off]).all()
    assert np.isfinite(rt.client_metrics[sel]).all()


# -------------------- prefetch / patch correctness --------------------- #

def test_prefetched_loop_matches_serial_rounds_bitwise():
    """The double-buffered loop (stale-row patch included) ends bitwise
    where the serial per-round tiered path ends — overlapping cohorts
    across rounds are exactly the case the patch exists for."""
    cfg = _cfg(num_participants=0.5, num_rounds=4)
    clients, data = _federation(6, cfg)
    serial = _tiered(cfg, data, 6)
    res_serial = [serial.run_round(r) for r in range(4)]
    pre = _tiered(cfg, data, 6)
    res_pre = _run(pre, 4)
    for a, b in zip(res_serial, res_pre):
        assert a.selected == b.selected and a.aggregator == b.aggregator
        np.testing.assert_array_equal(a.client_metrics, b.client_metrics)
    _assert_states_equal(serial.store.host, pre.store.host)
    s = pre.stats.summary()
    assert s["rounds"] == 4 and s["overlapped"]
    assert len(s["prefetch_gap_s"]) == 4


# ---------------- absolute-id keying / padding invariance --------------- #

def test_cohort_gather_keyed_to_absolute_ids_padding_invariant():
    """PARITY.md §8 for the cohort axis: growing the padded client axis
    (what a bigger mesh forces) changes NOTHING — same cohorts, same
    results, same tier. Rides alongside the fold_in init pins."""
    cfg = _cfg(num_rounds=3)
    clients, data = _federation(6, cfg)
    a = _tiered(cfg, data, 6)
    ra = _run(a, 3)
    b = _tiered(cfg, pad_federated_data(data, 6 + 4), 6)
    rb = _run(b, 3)
    for x, y in zip(ra, rb):
        assert x.selected == y.selected and x.aggregator == y.aggregator
        np.testing.assert_array_equal(x.client_metrics, y.client_metrics)
    _assert_states_equal(a.store.host, b.store.host)


# ------------------------- memory accounting --------------------------- #

def test_device_bytes_scale_with_cohort_not_fleet():
    """The acceptance's memory pin: the device-resident state slab scales
    with C (x8 for C 64 -> 512) and sits far below the dense layout's
    device bytes at the same N."""
    from fedmse_tpu.federation.state import dense_state_bytes

    cfg = _cfg()
    model = _model(cfg)
    tx = optax.adam(cfg.lr_rate)
    n = 4096
    tier = TieredClientStore.create(model, tx, jax.random.key(0), n)
    b64, b512 = tier.slab_bytes(64), tier.slab_bytes(512)
    assert b512 == 8 * b64
    # measured slab: gather C rows, sum the live device leaf bytes
    slab = tier.gather(np.arange(512, dtype=np.int32))
    measured = sum(int(l.nbytes) for l in jax.tree.leaves(slab))
    assert measured == b512
    dense_bytes = dense_state_bytes(jax.eval_shape(
        lambda: init_client_states(model, tx, jax.random.key(0), n)))
    assert dense_bytes >= (n // 512) * measured  # scales with N, slab with C


def test_100k_client_init_never_materializes_dense_device_tree():
    """A 100k-client tiered init holds the fleet in host numpy only: no
    live device array carries the fleet-sized leading axis (params OR f32
    Adam moments), and the device footprint of a C=512 round slab is
    >= 100x smaller than the dense tree would be."""
    from fedmse_tpu.federation.state import dense_state_bytes

    n = 100_000
    cfg = _cfg()
    model = make_model("hybrid", 6, 4, 2, cfg.shrink_lambda)
    tx = optax.adam(cfg.lr_rate)
    tier = TieredClientStore.create(model, tx, jax.random.key(1), n,
                                    init_chunk=8192)
    fleet_axis = [a for a in jax.live_arrays()
                  if a.shape and a.shape[0] == n]
    assert not fleet_axis, [a.shape for a in fleet_axis[:3]]
    assert tier.host.hist_perf.shape == (n,)
    dense_bytes = dense_state_bytes(jax.eval_shape(
        lambda: init_client_states(model, tx, jax.random.key(1), n)))
    assert dense_bytes / tier.slab_bytes(512) >= 100


# ----------------------- checkpoint interchange ------------------------ #

def test_checkpoints_interchange_between_layouts(tmp_path):
    from fedmse_tpu.checkpointing import CheckpointManager

    cfg = _cfg(num_rounds=2)
    clients, data = _federation(6, cfg)
    tier = _tiered(cfg, data, 6)
    _run(tier, 2)
    ck = CheckpointManager(str(tmp_path))
    ck.save("tag", tier.states_for_checkpoint(6), tier.host, 2)

    # tiered snapshot -> dense engine (device restore)
    cfg_d = cfg.replace(state_layout="dense")
    dense = RoundEngine(_model(cfg_d), cfg_d, data, n_real=6,
                        rngs=ExperimentRngs(run=0, data_seed=cfg.data_seed),
                        model_type="hybrid", update_type="mse_avg",
                        fused=True)
    st, host, ri, _ = ck.restore("tag", dense.states)
    assert ri == 2
    _assert_states_equal(jax.device_get(st), tier.store.host)

    # dense snapshot (pre-PR-11 layout) -> tier: host-owned numpy leaves
    ck.save("dense_tag", dense.states, dense.host, 1)
    st2, _, _, _ = ck.restore("dense_tag", tier.states_for_checkpoint(6),
                              layout="tiered")
    assert all(isinstance(l, np.ndarray) for l in jax.tree.leaves(st2))
    fresh = _tiered(cfg, data, 6)
    fresh.restore_states(st2)
    _assert_states_equal(fresh.store.host, jax.device_get(dense.states))


# -------------------------- fault/membership --------------------------- #

def test_chaos_at_cohort_width_smoke():
    cfg = _cfg(num_rounds=3)
    clients, data = _federation(6, cfg)
    eng = _tiered(cfg, data, 6, chaos=ChaosSpec(dropout_p=0.3, crash_p=0.2))
    res = _run(eng, 3)
    for r in res:
        assert r.divergence is not None
        assert set(r.effective) <= set(r.selected)


def test_elastic_tier_transitions_mutate_host_rows():
    """A join under the tiered layout mutates the HOST tier: the joiner's
    params row becomes the full-fleet incumbent mean, moments zero,
    history cleared (elastic.apply_membership_transitions)."""
    from fedmse_tpu.federation.elastic import apply_membership_transitions

    cfg = _cfg()
    model = _model(cfg)
    tx = optax.adam(cfg.lr_rate)
    tier = TieredClientStore.create(model, tx, jax.random.key(3), 5)
    # make history/moments visibly nonzero first
    for leaf in jax.tree.leaves(tier.host.opt_state):
        leaf += 1
    tier.host.hist_seen[:] = True
    tier.host.rejected[:] = 2
    before = jax.tree.map(np.copy, tier.host.params)
    member = np.array([1, 1, 1, 0, 1], np.float32)
    joined = np.array([0, 0, 0, 1, 0], np.float32)
    left = np.array([0, 1, 0, 0, 0], np.float32)
    member[3] = 1.0  # the joiner is a member this round
    apply_membership_transitions(tier, member, joined, left)
    w = np.array([1, 1, 1, 0, 1], np.float32) / 4.0
    for leaf, b in zip(jax.tree.leaves(tier.host.params),
                       jax.tree.leaves(before)):
        np.testing.assert_allclose(
            leaf[3], np.einsum("n,n...->...", w, b.astype(np.float32)
                               ).astype(leaf.dtype), rtol=1e-6)
    for leaf in jax.tree.leaves(tier.host.opt_state):
        assert (leaf[3] == 0).all() and (leaf[1] == 0).all()  # join + leave
        assert (leaf[0] == 1).all()                           # untouched
    assert not tier.host.hist_seen[3] and tier.host.rejected[3] == 0
    assert tier.host.hist_seen[0] and tier.host.rejected[0] == 2


def test_elastic_cohort_run_reports_roster():
    cfg = _cfg(num_rounds=3)
    clients, data = _federation(6, cfg)
    eng = _tiered(cfg, data, 6,
                  elastic=ElasticSpec(leave_p=0.3, join_p=0.5))
    res = _run(eng, 3)
    assert res[-1].members is not None and res[-1].generations is not None
    member = eng.members_at(3)
    fm = eng.evaluate_final_streamed()
    assert fm.shape == (6,)
    assert sorted(res[-1].members) == np.flatnonzero(member).tolist()


# ------------------------------ mesh slab ------------------------------ #

def test_cohort_slab_shards_over_client_mesh(mesh8):
    """C divisible by the mesh: the slab and cohort data shard P('clients')
    and the round agrees with the unsharded run (float-level: the sharded
    einsum merge may reorder the reduction)."""
    cfg = _cfg(num_participants=0.5, num_rounds=2)
    clients, data = _federation(32, cfg)
    plain = _tiered(cfg, data, 32)
    rp = _run(plain, 2)
    meshed = _tiered(cfg, data, 32, mesh=mesh8)
    assert meshed.cohort % 8 == 0
    rm = _run(meshed, 2)
    slab = meshed.store.gather(np.arange(meshed.cohort, dtype=np.int32),
                               place=meshed._place)
    leaf = jax.tree.leaves(slab)[0]
    assert leaf.sharding.shard_shape(leaf.shape)[0] == leaf.shape[0] // 8
    for a, b in zip(rp, rm):
        assert a.selected == b.selected and a.aggregator == b.aggregator
        np.testing.assert_allclose(a.client_metrics, b.client_metrics,
                                   rtol=1e-5, atol=1e-6)


# ------------------------------- guards -------------------------------- #

def test_dense_engines_reject_tiered_layout():
    from fedmse_tpu.federation.batched import BatchedRunEngine

    cfg = _cfg()
    clients, data = _federation(4, cfg)
    with pytest.raises(ValueError, match="TieredRoundEngine"):
        RoundEngine(_model(cfg), cfg, data, n_real=4,
                    rngs=ExperimentRngs(run=0, data_seed=cfg.data_seed),
                    model_type="hybrid", update_type="mse_avg", fused=True)
    with pytest.raises(ValueError, match="dense-layout only"):
        BatchedRunEngine(_model(cfg), cfg, data, n_real=4, runs=2,
                         model_type="hybrid", update_type="mse_avg")
    with pytest.raises(ValueError, match="state_layout"):
        RoundEngine(_model(cfg), cfg.replace(state_layout="bogus"), data,
                    n_real=4,
                    rngs=ExperimentRngs(run=0, data_seed=cfg.data_seed),
                    model_type="hybrid", update_type="mse_avg", fused=True)
