"""Network serving plane tests (fedmse_tpu/net/): wire framing, the
roster-aware router over >= 2 replicas (UNKNOWN_GATEWAY terminates at
the router, never inside a replica), tiered load shedding under
synthetic overload (injected clock; SHED verdicts exactly-once, lowest
tier first, never under capacity), hot-swap broadcast with per-replica
regime atomicity and zero dropped/duplicated admitted tickets, the
cost-aware SLO autoscaler, replica bucket resizing, and the asyncio
NetFront + NetClient loopback path."""

import numpy as np
import pytest

import jax

from fedmse_tpu.models import init_stacked_params, make_model
from fedmse_tpu.net import wire
from fedmse_tpu.net.admission import AdmissionController
from fedmse_tpu.net.autoscale import BackendSpec, SLOAutoscaler, plan_mix
from fedmse_tpu.net.client import NetClient, NetClientError
from fedmse_tpu.net.router import Router, make_local_replicas
from fedmse_tpu.net.server import FrontHandle, NetFront
from fedmse_tpu.serving import ServingRoster, fit_calibration
from fedmse_tpu.serving.engine import ServingEngine

pytestmark = pytest.mark.net

DIM = 12
N = 4


def _plane(n_replicas=2, max_batch=32, seed=0, tiers=3,
           capacity=None, clock=None, roster=None, model_type="hybrid",
           budget_ms=1e9):
    """A small serving plane over a synthetic federation: n_replicas
    engines sharing one stacked param tree, router + admission in front.
    `capacity` None leaves admission wide open (no shedding). `clock`
    (injected, frozen) drives ADMISSION + the router deterministically;
    the replica batchers keep the real clock (the loopback tests rely on
    budget-expiry flushes in the server's drive loop — pass a finite
    `budget_ms` there)."""
    rng = np.random.default_rng(seed)
    model = make_model(model_type, DIM, shrink_lambda=1.0)
    params = init_stacked_params(model, jax.random.key(seed), N)
    train_x = rng.normal(size=(N, 60, DIM)).astype(np.float32)
    # the roster goes to the ROUTER (the authoritative admission point),
    # not the engines: calibration fits through every slot, and the
    # roster-swap broadcast installs engine-side rosters when membership
    # actually changes
    engines = [ServingEngine.from_federation(
        model, model_type, params, train_x=train_x, max_bucket=max_batch)
        for _ in range(n_replicas)]
    cal = fit_calibration(
        engines[0], rng.normal(size=(N, 120, DIM)).astype(np.float32))
    kw = {} if clock is None else {"clock": clock}
    replicas = make_local_replicas(lambda i: engines[i], n_replicas,
                                   max_batch=max_batch,
                                   latency_budget_ms=budget_ms,
                                   calibration=cal)
    admission = AdmissionController(tiers=tiers, headroom=1.0,
                                    burst_s=1.0, **kw)
    if capacity is not None:
        admission.set_capacity(capacity)
    router = Router(replicas, admission=admission, roster=roster, **kw)
    rows = rng.normal(size=(600, DIM)).astype(np.float32)
    gws = rng.integers(0, N, 600).astype(np.int32)
    return model, params, train_x, router, cal, rows, gws


# ------------------------------- wire ---------------------------------- #

def test_wire_roundtrip_and_guards():
    rows = np.arange(12, dtype=np.float32).reshape(3, 4)
    gws = np.asarray([2, 0, 1], np.int32)
    tiers = np.asarray([0, 2, 1], np.uint8)
    buf = wire.FrameBuffer()
    buf.feed(wire.pack_submit(42, rows, gws, tiers))
    buf.feed(wire.pack_submit(43, rows, 1))  # broadcast gw, no tiers
    got = list(buf.frames())
    assert len(got) == 2
    rid, r2, g2, t2, t_sent = wire.unpack_submit(got[0])
    assert rid == 42 and t_sent > 0
    np.testing.assert_array_equal(r2, rows)
    np.testing.assert_array_equal(g2, gws)
    np.testing.assert_array_equal(t2, tiers)
    rid, _, g3, t3, _ = wire.unpack_submit(got[1])
    assert rid == 43 and g3.tolist() == [1, 1, 1] and t3.tolist() == [0] * 3
    # pre-packed-frame patching: the documented offsets hit the fields
    import struct as _struct
    frame = bytearray(wire.pack_submit(7, rows, gws, tiers, t_sent=1.0))
    _struct.pack_into("!Q", frame, wire.REQUEST_ID_OFFSET, 99)
    _struct.pack_into("!d", frame, wire.T_SENT_OFFSET, 123.5)
    rid, _, _, _, ts = wire.unpack_submit(memoryview(bytes(frame))[4:])
    assert rid == 99 and ts == 123.5
    # results round-trip statuses + scores (NaN preserved for shed rows)
    st = np.asarray([0, 2, 3], np.uint8)
    sc = np.asarray([1.5, np.nan, np.nan], np.float32)
    buf.feed(wire.pack_result(42, st, sc))
    rid, st2, sc2 = wire.unpack_result(next(iter(buf.frames())))
    assert rid == 42 and st2.tolist() == [0, 2, 3]
    assert sc2[0] == 1.5 and np.isnan(sc2[1:]).all()
    # a corrupt length prefix fails loudly, never allocates
    buf2 = wire.FrameBuffer()
    buf2.feed(b"\xff\xff\xff\xff")
    with pytest.raises(wire.WireError, match="MAX_FRAME"):
        list(buf2.frames())
    # truncated/inflated submit bodies are rejected
    frame = wire.pack_submit(1, rows, gws)
    with pytest.raises(wire.WireError, match="declared"):
        wire.unpack_submit(memoryview(frame[4:-2]))


# --------------------- routing + exactly-once ------------------------- #

def test_router_scores_match_oracle_exactly_once():
    """Bursts striped across 2 replicas resolve per-row scores equal to
    the blocking engine, in submission order, every row exactly once."""
    _, _, _, router, cal, rows, gws = _plane()
    results = [router.submit_many(rows[s:s + 100], gws[s:s + 100])
               for s in range(0, 600, 100)]
    router.drain()
    assert all(r.finalize() for r in results)
    got = np.concatenate([r.scores for r in results])
    eng = router.replicas[0].engine
    want = eng.score(rows, gws)
    np.testing.assert_allclose(got, want, atol=1e-5)
    statuses = np.concatenate([r.statuses for r in results])
    want_v = cal.verdicts(want, gws)
    np.testing.assert_array_equal(
        statuses, np.where(want_v, wire.STATUS_ANOMALY, wire.STATUS_NORMAL))
    # both replicas actually served traffic (the stripe is real)
    served = [r.stats()["rows_served"] for r in router.replicas]
    assert all(s > 0 for s in served) and sum(served) == 600
    assert router.stats()["rows_routed"] == 600


def test_finalize_passes_remote_statuses_through():
    """A remote replica's terminal statuses reach the RouteResult
    verbatim — a misdeployed worker's SHED/UNKNOWN verdicts are never
    relabeled as normal (router.RouteResult.finalize raw_statuses)."""
    from fedmse_tpu.net.router import RouteResult

    class FakeRemoteBlock:
        done = True
        scores = np.asarray([1.0, np.nan, np.nan], np.float32)
        verdicts = np.asarray([False, False, False])
        raw_statuses = np.asarray(
            [wire.STATUS_ANOMALY, wire.STATUS_SHED,
             wire.STATUS_UNKNOWN_GATEWAY], np.uint8)

    res = RouteResult(3)
    res._segs.append((FakeRemoteBlock(), np.arange(3)))
    assert res.finalize()
    assert res.statuses.tolist() == [wire.STATUS_ANOMALY, wire.STATUS_SHED,
                                     wire.STATUS_UNKNOWN_GATEWAY]


def test_router_unknown_gateway_terminates_at_router():
    """A retired slot's rows get STATUS_UNKNOWN_GATEWAY from the ROUTER;
    no replica dispatch ever sees them (dispatch counters pinned), and
    surviving rows in the same burst still score."""
    roster = ServingRoster(member=np.asarray([True, True, False, True]),
                           generation=np.asarray([0, 0, 1, 0]))
    _, _, _, router, _, rows, gws = _plane(roster=roster)
    gws = np.asarray([0, 1, 3], np.int32)[gws % 3]  # live slots only
    gws = gws.copy()
    gws[:20] = 2  # route the first 20 rows at the retired slot
    before = [dict(rep.engine.dispatches) for rep in router.replicas]
    res = router.submit_many(rows[:100], gws[:100])
    router.drain()
    assert res.finalize()
    assert (res.statuses[:20] == wire.STATUS_UNKNOWN_GATEWAY).all()
    assert np.isnan(res.scores[:20]).all()
    assert (res.statuses[20:] != wire.STATUS_UNKNOWN_GATEWAY).all()
    assert not np.isnan(res.scores[20:]).any()
    # the retired rows never reached a replica: only the 80 survivors
    # were dispatched (padded buckets counted by bucket size)
    served = sum(rep.stats()["rows_served"] for rep in router.replicas)
    assert served == 80
    del before
    assert router.stats()["rows_unknown_gateway"] == 20


def test_roster_swap_mid_load_retires_and_broadcasts():
    """A mid-stream roster swap flips admission at the router for the
    very next burst and broadcasts to every replica (their engines see
    the new roster too); rows admitted before the swap still resolve."""
    _, _, _, router, _, rows, gws = _plane()
    gws = gws.copy()
    gws[:] = np.arange(600) % N
    r1 = router.submit_many(rows[:100], gws[:100])
    retired = ServingRoster(
        member=np.asarray([True, True, False, True]),
        generation=np.asarray([0, 0, 1, 0]))
    event = router.swap(roster=retired)
    assert event["replicas"] == len(router.replicas)
    r2 = router.submit_many(rows[100:200], gws[100:200])
    router.drain()
    assert r1.finalize() and r2.finalize()
    assert (r1.statuses != wire.STATUS_UNKNOWN_GATEWAY).all()
    mask2 = gws[100:200] == 2
    assert (r2.statuses[mask2] == wire.STATUS_UNKNOWN_GATEWAY).all()
    assert (r2.statuses[~mask2] != wire.STATUS_UNKNOWN_GATEWAY).all()
    for rep in router.replicas:
        assert rep.engine.roster is retired


# ----------------------------- shedding -------------------------------- #

def test_no_shedding_under_capacity():
    """Offered load below measured capacity sheds NOTHING (shedding may
    engage only beyond capacity — the acceptance contract)."""
    now = [0.0]
    _, _, _, router, _, rows, gws = _plane(capacity=10_000.0,
                                           clock=lambda: now[0])
    results = []
    for s in range(0, 600, 100):  # 100 rows per 100 ms = 1k rows/s
        results.append(router.submit_many(rows[s:s + 100], gws[s:s + 100],
                                          tiers=(np.arange(100) % 3)))
        now[0] += 0.1
    router.drain()
    assert all(r.finalize() for r in results)
    statuses = np.concatenate([r.statuses for r in results])
    assert (statuses != wire.STATUS_SHED).all()
    assert router.admission.stats()["shed_total"] == 0


def test_shedding_lowest_tier_first_exactly_once():
    """Sustained overload sheds lowest-priority tiers first, every row
    still gets exactly one terminal status, and admitted rows all
    score — zero silent drops under overload."""
    now = [0.0]
    # capacity 1000 rows/s, bucket depth 1000 tokens (burst_s=1)
    _, _, _, router, _, rows, gws = _plane(capacity=1000.0,
                                           clock=lambda: now[0])
    tiers = np.asarray([0, 1, 2] * 200, np.uint8)  # even tier mix
    # instant 600-row burst: bucket holds 1000 -> all admitted
    r1 = router.submit_many(rows, gws, tiers=tiers)
    # no time passes: the next 600-row burst finds only 400 tokens
    r2 = router.submit_many(rows, gws, tiers=tiers)
    router.drain()
    assert r1.finalize() and r2.finalize()
    assert (r1.statuses != wire.STATUS_SHED).all()
    shed2 = r2.statuses == wire.STATUS_SHED
    assert shed2.sum() == 200
    # strict priority: the 400 admitted tokens cover all of tier 0 and
    # tier 1 (200 each); every tier-2 row is shed, nothing above it is
    assert (tiers[shed2] == 2).all()
    assert (r2.statuses[tiers == 0] != wire.STATUS_SHED).all()
    assert (r2.statuses[tiers == 1] != wire.STATUS_SHED).all()
    # exactly-once: every non-shed row carries a real score, every shed
    # row carries none, and the admitted count balances
    assert not np.isnan(r2.scores[~shed2]).any()
    assert np.isnan(r2.scores[shed2]).all()
    st = router.admission.stats()
    assert st["shed_by_tier"] == [0, 0, 200]
    assert st["shed_total"] == 200 and st["shed_events"] >= 1
    assert st["offered_by_tier"] == [400, 400, 400]
    # refill: a second's worth of tokens re-opens admission
    now[0] += 1.0
    r3 = router.submit_many(rows[:300], gws[:300], tiers=tiers[:300])
    router.drain()
    assert r3.finalize()
    assert (r3.statuses != wire.STATUS_SHED).all()


def test_staleness_shed_is_tier_ordered_and_spares_tier0():
    """The self-correcting overload gate: a burst that already queued
    past the budget sheds its lowest tiers first (tier k at
    stale_after * (tiers - k)) and NEVER tier 0 — whatever the capacity
    probe believed (admission.py docstring)."""
    adm = AdmissionController(tiers=3, stale_after_s=0.025,
                              clock=lambda: 0.0)
    tiers = np.asarray([0, 1, 2] * 4, np.uint8)
    # fresh burst: nothing sheds (no capacity set, age under budget)
    assert adm.admit(tiers, now=0.0, age_s=0.01).all()
    # age past 1x budget: tier 2 sheds, tiers 0/1 ride
    m = adm.admit(tiers, now=0.0, age_s=0.03)
    assert (~m).sum() == 4 and (tiers[~m] == 2).all()
    # age past 2x budget: tiers 1+2 shed, tier 0 still rides
    m = adm.admit(tiers, now=0.0, age_s=0.06)
    assert (tiers[~m] >= 1).all() and m[tiers == 0].all()
    assert (~m).sum() == 8
    # arbitrarily old: tier 0 is the guaranteed tier
    m = adm.admit(tiers, now=0.0, age_s=1e9)
    assert m[tiers == 0].all() and not m[tiers > 0].any()
    st = adm.stats()
    assert st["shed_by_tier"][0] == 0
    assert st["shed_by_tier"][1] <= st["shed_by_tier"][2]


def test_constructor_capacity_arms_a_full_bucket():
    """A controller BUILT with a capacity starts with a full bucket —
    the first burst after construction can never shed (same arming
    rule as set_capacity)."""
    adm = AdmissionController(tiers=3, capacity_rows_per_sec=100.0,
                              headroom=1.0, burst_s=1.0,
                              clock=lambda: 0.0)
    assert adm.admit(np.asarray([0, 1, 2] * 30), now=0.0).all()
    assert adm.stats()["shed_total"] == 0


def test_partial_tier_shed_keeps_arrival_order():
    """When the boundary tier only partially fits, earlier rows of that
    tier win (arrival order within a tier)."""
    adm = AdmissionController(tiers=2, headroom=1.0, burst_s=1.0,
                              clock=lambda: 0.0)
    adm.set_capacity(10.0)  # 10 tokens in the bucket
    tiers = np.asarray([1, 0, 1, 1, 0, 1, 1, 1, 1, 1, 1, 1], np.uint8)
    admit = adm.admit(tiers, now=0.0)
    # both tier-0 rows admitted; the first 8 tier-1 rows fill the rest
    assert admit[[1, 4]].all()
    t1_pos = np.flatnonzero(tiers == 1)
    assert admit[t1_pos[:8]].all() and not admit[t1_pos[8:]].any()


# ------------------------- swap during load ---------------------------- #

def test_params_swap_mid_load_atomic_per_replica():
    """A checkpoint+thresholds broadcast mid-load: every replica's
    in-flight batch keeps the old regime, later batches score under the
    new one, zero tickets dropped or duplicated across >= 2 replicas,
    and no replica retraces."""
    model, params, train_x, router, cal, rows, gws = _plane(max_batch=16)
    params2 = init_stacked_params(model, jax.random.key(9), N)
    eng_old = router.replicas[0].engine
    eng2 = ServingEngine.from_federation(model, "hybrid", params2,
                                         train_x=train_x, max_bucket=16)
    from fedmse_tpu.serving.engine import fit_gateway_centroids
    cens2 = fit_gateway_centroids(model, params2, train_x)
    want_old = eng_old.score(rows, gws)
    want_new = eng2.score(rows, gws)

    for rep in router.replicas:  # compile every bucket BEFORE the pin
        rep.engine.warmup()
    caches = [rep.engine._scorer()._cache_size()
              for rep in router.replicas]
    results = []
    for s in range(0, 300, 50):  # fills both replicas' pipelines
        results.append(router.submit_many(rows[s:s + 50], gws[s:s + 50]))
    event = router.swap(params=params2, centroids=cens2)
    for s in range(300, 600, 50):
        results.append(router.submit_many(rows[s:s + 50], gws[s:s + 50]))
    router.drain()
    assert event["replicas"] == 2
    assert all(rep.engine.swap_count == 1 for rep in router.replicas)
    assert all(rep.engine._scorer()._cache_size() == c
               for rep, c in zip(router.replicas, caches))  # zero retrace
    assert all(r.finalize() for r in results)
    got = np.concatenate([r.scores for r in results])
    assert len(got) == 600 and not np.isnan(got).any()
    # per-batch atomicity: every row matches the old oracle or the new
    # one — never a mixture within a row's batch. Rows DISPATCHED before
    # the broadcast keep the old regime (the first full slices certainly
    # were); every row submitted after it scores new. Rows still FORMING
    # at the swap score under the incoming state — the documented
    # ContinuousBatcher boundary, which is why the pre-swap range is not
    # pinned all-old wholesale.
    old_ok = np.isclose(got, want_old, atol=1e-5)
    new_ok = np.isclose(got, want_new, atol=1e-5)
    assert (old_ok | new_ok).all()
    assert old_ok[:32].all()      # first slice per replica: in flight
    assert new_ok[300:].all()
    served = sum(rep.stats()["rows_served"] for rep in router.replicas)
    assert served == 600  # exactly once, nothing re-scored


# ----------------------------- autoscaler ------------------------------ #

CPU = BackendSpec("cpu", rows_per_sec=100_000.0, usd_per_hour=0.10,
                  max_replicas=8)
TPU = BackendSpec("tpu", rows_per_sec=2_000_000.0, usd_per_hour=1.20,
                  max_replicas=4)


def test_cost_model_crossover():
    """The 2509.14920 shape: the accelerator is cheaper PER ROW at full
    utilization, yet all-CPU wins below its amortization point because
    a fractional accelerator cannot be bought."""
    assert TPU.usd_per_megarow < CPU.usd_per_megarow
    low = plan_mix(50_000.0, [CPU, TPU], target_utilization=1.0)
    assert low == {"cpu": 1, "tpu": 0}
    mid = plan_mix(500_000.0, [CPU, TPU], target_utilization=1.0)
    assert mid["cpu"] * CPU.rows_per_sec + mid["tpu"] * TPU.rows_per_sec \
        >= 500_000.0
    # 5 CPU replicas would cost 0.50/h; one TPU covers it for 1.20/h —
    # CPU still wins here; at 4M rows/s CPU cannot even cover (8 max)
    assert mid == {"cpu": 5, "tpu": 0}
    high = plan_mix(4_000_000.0, [CPU, TPU], target_utilization=1.0)
    assert high["tpu"] >= 2
    cost_high = (high["cpu"] * CPU.usd_per_hour
                 + high["tpu"] * TPU.usd_per_hour)
    # the mix picked is the cheapest covering one
    assert cost_high <= 8 * CPU.usd_per_hour + 4 * TPU.usd_per_hour


def test_autoscaler_budget_and_hysteresis():
    now = [0.0]
    sc = SLOAutoscaler(budget_ms=10.0, backends=[CPU, TPU],
                       target_utilization=0.6, scale_down_utilization=0.3,
                       min_bucket=64, max_bucket=4096, cooldown_s=5.0,
                       clock=lambda: now[0])
    # demand above one CPU replica's 60%-utilized supply: scale up
    d = sc.decide(arrival_rows_per_sec=150_000.0, p99_ms=4.0,
                  current={"cpu": 1})
    assert d.action == "scale_up" and d.total_replicas >= 3
    sc.mark_applied()
    # inside the cooldown every decision holds, whatever the signal
    now[0] += 1.0
    d = sc.decide(arrival_rows_per_sec=150_000.0, p99_ms=50.0,
                  current={"cpu": 1})
    assert d.action == "hold" and d.reason == "cooldown"
    now[0] += 10.0
    # p99 breach without a demand case still scales up (and shrinks the
    # bucket: smaller dispatches drain the forming window sooner)
    d = sc.decide(arrival_rows_per_sec=30_000.0, p99_ms=50.0,
                  current={"cpu": 1})
    assert d.action == "scale_up"
    healthy = sc._pick_bucket(30_000.0, 1, p99_ms=None)
    assert d.bucket <= healthy
    sc.mark_applied()
    now[0] += 10.0
    # utilization far below the low watermark: scale down to the
    # cheapest covering mix
    d = sc.decide(arrival_rows_per_sec=10_000.0, p99_ms=2.0,
                  current={"cpu": 4})
    assert d.action == "scale_down" and d.total_replicas == 1
    # bucket targets the largest pow2 the per-replica share fills
    assert sc._pick_bucket(1_600_000.0, 2, p99_ms=None) == 4096
    assert sc._pick_bucket(12_800.0, 1, p99_ms=None) == 128


def test_replica_resize_preserves_service():
    _, _, _, router, _, rows, gws = _plane(max_batch=32)
    r1 = router.submit_many(rows[:100], gws[:100])
    for rep in router.replicas:
        rep.resize(8)
    r2 = router.submit_many(rows[100:200], gws[100:200])
    router.drain()
    assert r1.finalize() and r2.finalize()
    assert all(rep.max_batch == 8 for rep in router.replicas)
    eng = router.replicas[0].engine
    np.testing.assert_allclose(
        np.concatenate([r1.scores, r2.scores]),
        eng.score(rows[:200], gws[:200]), atol=1e-5)


# --------------------------- TCP loopback ------------------------------ #

def test_net_front_loopback_end_to_end():
    """The full socket path: NIC-poll bursts over localhost TCP through
    2 replicas, mixed tiers, a retired-gateway burst, a mid-stream
    threshold swap broadcast, stats over the wire — per-row statuses
    and scores equal to the in-process oracle, exactly once."""
    roster = ServingRoster(member=np.asarray([True, True, True, False]),
                           generation=np.asarray([0, 0, 0, 1]))
    _, _, _, router, cal, rows, gws = _plane(roster=roster, budget_ms=5.0)
    gws = np.arange(600, dtype=np.int32) % (N - 1)  # live slots only
    eng = router.replicas[0].engine
    want = eng.score(rows, gws)
    handle = FrontHandle(NetFront(router))
    try:
        client = NetClient("127.0.0.1", handle.port)
        rids = [client.submit(rows[s:s + 100], gws[s:s + 100],
                              tiers=(np.arange(100) % 3))
                for s in range(0, 300, 100)]
        # a burst aimed at the retired slot resolves UNKNOWN over the wire
        bad_rid = client.submit(rows[:10], np.full(10, N - 1, np.int32))
        event = client.swap({"calibration": cal})  # mid-stream broadcast
        assert event["kinds"] == ["thresholds"] and event["replicas"] == 2
        rids += [client.submit(rows[s:s + 100], gws[s:s + 100])
                 for s in range(300, 600, 100)]
        client.wait_all()
        got = np.concatenate([client.results[r][1] for r in rids])
        np.testing.assert_allclose(got, want, atol=1e-5)
        st_bad = client.results[bad_rid][0]
        assert (st_bad == wire.STATUS_UNKNOWN_GATEWAY).all()
        counts = client.status_counts()
        assert counts["unknown_gateway"] == 10 and counts["shed"] == 0
        assert sum(counts.values()) == client.rows_submitted == 610
        stats = client.stats()
        assert stats["router"]["replicas"] == 2
        assert stats["router"]["rows_served"] == 600
        assert stats["requests"] == 7
        # a malformed swap reports on the wire without killing serving
        with pytest.raises(NetClientError, match="nothing to swap"):
            client.swap({})
        tail = client.submit(rows[:50], gws[:50])
        client.wait_all()
        np.testing.assert_allclose(client.results[tail][1], want[:50],
                                   atol=1e-5)
        client.close()
    finally:
        handle.stop()


def test_shed_verdicts_over_the_wire():
    """Overload through the socket: shed rows come back as explicit
    STATUS_SHED frames (never dropped responses), admitted rows score."""
    now = [0.0]
    _, _, _, router, _, rows, gws = _plane(capacity=1000.0,
                                           clock=lambda: now[0],
                                           budget_ms=5.0)
    handle = FrontHandle(NetFront(router))
    try:
        client = NetClient("127.0.0.1", handle.port)
        tiers = np.asarray([0, 1, 2] * 200, np.uint8)
        r1 = client.submit(rows, gws, tiers=tiers)      # fills the bucket
        r2 = client.submit(rows, gws, tiers=tiers)      # overload
        client.wait_all()
        st1, st2 = client.results[r1][0], client.results[r2][0]
        assert (st1 != wire.STATUS_SHED).all()
        shed = st2 == wire.STATUS_SHED
        assert shed.sum() == 200 and (tiers[shed] == 2).all()
        assert sum(client.status_counts().values()) == 1200
        client.close()
    finally:
        handle.stop()


def test_cli_serve_net(tmp_path):
    """--serve-net: the network-plane smoke end to end (train ->
    checkpoint -> replicas -> router + admission -> localhost TCP ->
    verdicts, with the mid-stream threshold-swap broadcast)."""
    import json
    import os

    from fedmse_tpu.config import DatasetConfig
    from fedmse_tpu.main import main as cli_main
    from tests.test_data import _write_client_csvs

    root = str(tmp_path / "shards")
    _write_client_csvs(root, 4, dim=6, n_normal=60, n_abnormal=24)
    cfg_path = os.path.join(root, "config.json")
    with open(cfg_path, "w") as f:
        json.dump(DatasetConfig.for_client_dirs(root, 4).to_json(), f)
    out = cli_main([
        "--dataset-config", cfg_path,
        "--model-types", "hybrid", "--update-types", "mse_avg",
        "--network-size", "4", "--dim-features", "6",
        "--epochs", "1", "--num-rounds", "1", "--batch-size", "8",
        "--checkpoint-dir", str(tmp_path / "ckpt"),
        "--experiment-name", "serve-net", "--serve-rows", "256",
        "--serve-net", "--net-replicas", "2", "--serve-max-batch", "64",
    ])
    smoke = out["net_smoke"]
    assert smoke["replicas"] == 2 and smoke["port"] > 0
    assert smoke["rows_streamed"] > 0
    assert smoke["zero_dropped"] is True
    assert smoke["swap_broadcast"] is True
    counts = smoke["statuses"]
    assert sum(counts.values()) == smoke["rows_streamed"]
    assert counts["shed"] == 0 and counts["unknown_gateway"] == 0
    assert smoke["request_p99_ms"] > 0
    assert smoke["router"]["rows_served"] == smoke["rows_streamed"]
    json.dumps(smoke)
