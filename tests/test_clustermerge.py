"""Clustered quantized collectives + cross-replica optimizer sharding
(DESIGN.md §23): the K-cluster merge as per-device [K, ...] partial sheets
with ONE psum over the stacked cluster rows (shard_map twin pinned BITWISE
to the einsum lowering), the hierarchical int8 variant per cluster row
(pinned within the clustered error bound ASSERTED FROM ACTUAL HOST
PARTIALS), the K=1 degeneracies (same executable by construction), the
ZeRO-style sharded Adam application (bitwise vs replicated), the measured
merge cost model, and the effective-backend recording that makes a silent
f32 fallback impossible to mistake for a quantized capture. All on the
session-shared 8-virtual-device CPU mesh (tests/conftest.py::mesh8)."""

import logging

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from fedmse_tpu.cluster.merge import make_clustered_aggregate_fn
from fedmse_tpu.config import CompatConfig, ExperimentConfig
from fedmse_tpu.data import build_dev_dataset, stack_clients, synthetic_clients
from fedmse_tpu.federation import RoundEngine
from fedmse_tpu.federation.state import (init_client_states,
                                         make_sharded_client_update)
from fedmse_tpu.models import init_stacked_params, make_model
from fedmse_tpu.parallel import (make_clustered_hierarchical_aggregate,
                                 make_clustered_shardmap_aggregate,
                                 make_hierarchical_aggregate,
                                 make_shardmap_aggregate, merge_profile,
                                 plan_merge, seam, shard_clients,
                                 shard_federation)
from fedmse_tpu.parallel.quantize import (clustered_quantization_error_bound,
                                          dequantize_sum_k,
                                          quantization_error_bound,
                                          quantize_blockwise,
                                          quantize_blockwise_k)
from fedmse_tpu.utils.seeding import ExperimentRngs

pytestmark = pytest.mark.clustermerge

DIM = 10
N = 16
K = 8


@pytest.fixture(scope="module")
def model():
    return make_model("hybrid", DIM, shrink_lambda=3.0)


@pytest.fixture(scope="module")
def inputs(model):
    rng = np.random.default_rng(7)
    params = init_stacked_params(model, jax.random.key(0), N)
    sel = jnp.asarray(rng.integers(0, 2, N).astype(np.float32).clip(0, 1))
    sel = sel.at[:2].set(1.0)  # at least one selected client
    dev = jnp.asarray(rng.normal(size=(32, DIM)).astype(np.float32))
    # every cluster row populated, assignment not device-aligned
    cluster = jnp.asarray((np.arange(N) * 3) % K, jnp.int32)
    return params, sel, dev, cluster


def sharded(inputs, mesh8):
    params, sel, dev, cluster = inputs
    return (shard_clients(params, mesh8), shard_clients(sel, mesh8), dev,
            shard_clients(cluster, mesh8))


# ------------------------- leading-K codec ------------------------- #

def test_codec_k1_degenerates_to_blockwise(rng):
    x = jnp.asarray(rng.normal(size=(3, 130)).astype(np.float32))
    qk, sk = quantize_blockwise_k(x[None], 64)
    q1, s1 = quantize_blockwise(x, 64)
    np.testing.assert_array_equal(np.asarray(qk)[0], np.asarray(q1))
    np.testing.assert_array_equal(np.asarray(sk)[0], np.asarray(s1))
    bk = clustered_quantization_error_bound(x[None], 64)
    assert bk.shape == (1,)
    assert bk[0] == quantization_error_bound(x, 64)


def test_codec_k_roundtrip_within_per_row_bound(rng):
    k = 5
    x = rng.normal(size=(k, 7, 19)).astype(np.float32)
    x[2] *= 40.0  # one hot row must not inflate the other rows' bounds
    q, s = quantize_blockwise_k(jnp.asarray(x), 32)
    assert q.dtype == jnp.int8 and q.shape[0] == k
    back = np.asarray(dequantize_sum_k(q[None], s[None], x.shape))
    bound = clustered_quantization_error_bound(x, 32)
    err = np.abs(back - x).reshape(k, -1).max(axis=1)
    assert np.all(err <= bound + 1e-7), (err, bound)
    # per-row bounds: the quiet rows' bounds stay small despite row 2
    assert bound[0] < bound[2] / 10


# ------------------- clustered explicit collectives ------------------- #

@pytest.mark.parametrize("update_type", ["avg", "mse_avg"])
def test_clustered_shardmap_bitwise_einsum(inputs, mesh8, model,
                                           update_type):
    """K=8 per-device partial sheets + one psum over the K-stacked tree is
    BITWISE the clustered einsum lowering on the same mesh — params,
    weights, and has_update."""
    params_s, sel_s, dev, cluster_s = sharded(inputs, mesh8)
    ein = make_clustered_aggregate_fn(model, update_type, K)
    sm = make_clustered_shardmap_aggregate(model, update_type, mesh8, K)
    cp_e, w_e, h_e = ein(params_s, sel_s, dev, cluster_s)
    cp_s, w_s, h_s = sm(params_s, sel_s, dev, cluster_s)
    for a, b in zip(jax.tree.leaves(cp_e), jax.tree.leaves(cp_s)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(w_e), np.asarray(w_s))
    np.testing.assert_array_equal(np.asarray(h_e), np.asarray(h_s))


@pytest.mark.parametrize("update_type", ["avg", "mse_avg"])
def test_k1_clustered_pins_bitwise_to_single_global(inputs, mesh8, model,
                                                    update_type):
    """K=1 clustered builders wrap the EXACT single-global program (same
    executable by construction, the ClusterSpec(k=1).is_null precedent) —
    so the quantized K=1 merge is bitwise the existing hierarchical one."""
    params_s, sel_s, dev, _ = sharded(inputs, mesh8)
    zeros = shard_clients(jnp.zeros(N, jnp.int32), mesh8)
    base_q = make_hierarchical_aggregate(model, update_type, mesh8,
                                         num_groups=4, block_size=64)
    clu_q = make_clustered_hierarchical_aggregate(model, update_type, mesh8,
                                                  1, num_groups=4,
                                                  block_size=64)
    agg, w = base_q(params_s, sel_s, dev)
    cp, cw, ch = clu_q(params_s, sel_s, dev, zeros)
    for a, b in zip(jax.tree.leaves(agg), jax.tree.leaves(cp)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b)[0])
    np.testing.assert_array_equal(np.asarray(w), np.asarray(cw))
    assert np.asarray(ch).shape == (1,) and bool(np.asarray(ch)[0])

    base_s = make_shardmap_aggregate(model, update_type, mesh8)
    clu_s = make_clustered_shardmap_aggregate(model, update_type, mesh8, 1)
    agg, w = base_s(params_s, sel_s, dev)
    cp, cw, _ = clu_s(params_s, sel_s, dev, zeros)
    for a, b in zip(jax.tree.leaves(agg), jax.tree.leaves(cp)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b)[0])
    np.testing.assert_array_equal(np.asarray(w), np.asarray(cw))


@pytest.mark.parametrize("update_type", ["avg", "mse_avg"])
def test_clustered_quantized_within_bound_from_host_partials(
        inputs, mesh8, model, update_type):
    """K=8 hierarchical int8 vs the exact clustered einsum: the per-cluster
    error must stay within the §23 composed bound Σ_h bound(P^(h))[k],
    where each P^(h) is the ACTUAL host-group partial sheet recomputed on
    host from the same inputs (4 emulated host groups of 2 devices) — the
    bound is asserted against real partials, not a modeled proxy."""
    params, sel, dev, cluster = inputs
    params_s, sel_s, dev_s, cluster_s = sharded(inputs, mesh8)
    ein = make_clustered_aggregate_fn(model, update_type, K)
    quant = make_clustered_hierarchical_aggregate(model, update_type, mesh8,
                                                  K, num_groups=4,
                                                  block_size=64)
    cp_e, w_e, h_e = ein(params_s, sel_s, dev_s, cluster_s)
    cp_q, w_q, h_q = quant(params_s, sel_s, dev_s, cluster_s)
    # control-plane tensors are NEVER quantized: bitwise across backends
    np.testing.assert_array_equal(np.asarray(w_e), np.asarray(w_q))
    np.testing.assert_array_equal(np.asarray(h_e), np.asarray(h_q))

    # normalized sheet row k, col n = one_hot * raw_n / row_sum_k — and the
    # returned weights ARE that column sum, so sheet * w recovers it
    one_hot = (np.asarray(cluster)[None, :]
               == np.arange(K)[:, None]).astype(np.float64)
    sheetw = one_hot * np.asarray(w_e, np.float64)[None, :]
    rows_per_group = N // 4
    for leaf_e, leaf_q, leaf_p in zip(jax.tree.leaves(cp_e),
                                      jax.tree.leaves(cp_q),
                                      jax.tree.leaves(params)):
        lp = np.asarray(leaf_p, np.float64)
        bound = np.zeros(K)
        for g in range(4):
            cols = slice(g * rows_per_group, (g + 1) * rows_per_group)
            partial = np.einsum("kn,n...->k...", sheetw[:, cols], lp[cols])
            bound += clustered_quantization_error_bound(
                partial.astype(np.float32), 64)
        err = np.abs(np.asarray(leaf_e, np.float64)
                     - np.asarray(leaf_q, np.float64)).reshape(K, -1)
        assert np.all(err.max(axis=1) <= bound + 1e-6), (err.max(axis=1),
                                                         bound)


def test_empty_cluster_rows_inert(inputs, mesh8, model):
    """A cluster row with no selected member must come back all-zero with
    has_update False — never NaN from a 0/0 normalization."""
    params_s, sel_s, dev, _ = sharded(inputs, mesh8)
    # every client in rows 0..3: rows 4..7 empty
    cluster4 = shard_clients(jnp.asarray(np.arange(N) % 4, jnp.int32), mesh8)
    for fn in (make_clustered_shardmap_aggregate(model, "avg", mesh8, K),
               make_clustered_hierarchical_aggregate(
                   model, "avg", mesh8, K, num_groups=4, block_size=64)):
        cp, w, h = fn(params_s, sel_s, dev, cluster4)
        h = np.asarray(h)
        assert h[:4].all() and not h[4:].any()
        for leaf in jax.tree.leaves(cp):
            leaf = np.asarray(leaf)
            assert np.all(np.isfinite(leaf))
            np.testing.assert_array_equal(leaf[4:], 0.0)


# ---------------- ZeRO-style sharded optimizer update ---------------- #

def test_sharded_adam_update_bitwise_vs_replicated(mesh8, model):
    """Applying one Adam step with every moment leaf pinned P('clients')
    produces bitwise the replicated application, and the outputs live
    sharded — each replica materialized only its partition of the
    moments (the §23 ZeRO seam)."""
    tx = optax.adam(1e-3)
    states = init_client_states(model, tx, jax.random.key(3), N)
    grads = jax.tree.map(
        lambda t: (jnp.arange(t.size, dtype=jnp.float32)
                   .reshape(t.shape) % 7 - 3) * 0.01, states.params)
    rep = make_sharded_client_update(tx)
    shd = make_sharded_client_update(tx, mesh8)
    p_r, o_r = rep(grads, states.opt_state, states.params)
    p_s, o_s = shd(grads, states.opt_state, states.params)
    for a, b in zip(jax.tree.leaves(p_r), jax.tree.leaves(p_s)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(o_r), jax.tree.leaves(o_s)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for leaf in jax.tree.leaves(p_s) + jax.tree.leaves(o_s):
        if leaf.ndim and leaf.shape[0] == N:
            assert not leaf.sharding.is_fully_replicated


# --------------------- measured merge cost model --------------------- #

def test_merge_profile_formulas():
    prof = merge_profile(backend="quantized", elem_counts=[1000, 24],
                         k=4, n_devices=8, n_groups=2, per_group=4,
                         block_size=64)
    # 1000 elems -> 16 blocks of 64 (lane-aligned at per=4), 24 -> 4 blocks
    assert prof["dcn_payload_bytes"] == 4 * (16 + 4) * (64 + 4)
    assert prof["dcn_bytes"] == 2 * 1 * prof["dcn_payload_bytes"]
    assert prof["merged_elems"] == 4 * 1024
    # H=2 is where the hierarchy wins big (the module-docstring ~6.8x)
    assert prof["dcn_reduction_vs_f32"] > 4.0


def test_plan_merge_measured_search(mesh8):
    elems = [353, 64]
    plan = plan_merge(mesh8, elems, k=4, group_counts=(2, 4),
                      block_sizes=(64, 256), repeats=1)
    assert plan["chosen"]["backend"] in ("shard_map", "quantized")
    # flat baseline + 2 groups x 2 block sizes, every row measured
    assert len(plan["candidates"]) == 5
    for c in plan["candidates"]:
        assert c["wall_s"] > 0.0 and np.isfinite(c["score_s"])
    backends = {c["backend"] for c in plan["candidates"]}
    assert backends == {"shard_map", "quantized"}
    assert plan["merged_elems"] == 4 * sum(elems)


def test_seam_records_clustered_quantized_profile(inputs, mesh8, model):
    seam.reset()
    params_s, sel_s, dev, cluster_s = sharded(inputs, mesh8)
    fn = make_clustered_hierarchical_aggregate(model, "avg", mesh8, K,
                                               num_groups=4, block_size=64)
    fn(params_s, sel_s, dev, cluster_s)
    prof = seam.snapshot()["merge_profiles"]["quantized"]
    assert prof["k"] == K and prof["n_groups"] == 4
    assert prof["dcn_bytes"] > 0
    assert prof["dcn_bytes_f32_same_topology"] > prof["dcn_bytes"]


# ------------------ effective-backend recording ------------------ #

class _LogCapture(logging.Handler):
    def __init__(self):
        super().__init__(logging.DEBUG)
        self.records = []

    def emit(self, record):
        self.records.append(record)


@pytest.fixture
def pkg_log():
    root = logging.getLogger("fedmse_tpu")
    handler = _LogCapture()
    old_level = root.level
    root.addHandler(handler)
    root.setLevel(logging.DEBUG)
    yield handler
    root.setLevel(old_level)
    root.removeHandler(handler)


@pytest.fixture(scope="module")
def federation():
    clients = synthetic_clients(n_clients=6, dim=DIM, n_normal=96,
                                n_abnormal=40)
    dev_x = build_dev_dataset(clients, ExperimentRngs(run=0).data_rng)
    return stack_clients(clients, dev_x, 8, pad_clients_to=8)


def _engine(data, model, backend, mesh=None, **cfg_kw):
    cfg = ExperimentConfig(dim_features=DIM, network_size=6, epochs=1,
                           batch_size=8, aggregation_backend=backend,
                           compat=CompatConfig(vote_tie_break=False),
                           **cfg_kw)
    return RoundEngine(model, cfg, data, n_real=6,
                       rngs=ExperimentRngs(run=0), model_type="hybrid",
                       update_type="mse_avg", fused=True, mesh=mesh)


def test_off_mesh_degrade_warns_and_records(federation, model, pkg_log):
    """The einsum fallback is LOUD (WARNING, was DEBUG) and the effective
    backend lands in the RoundResult — a silent f32 fallback can never
    masquerade as a quantized capture."""
    eng = _engine(federation, model, "quantized")
    assert eng.agg_backend == "einsum"
    warned = [r for r in pkg_log.records if "inert" in r.getMessage()]
    assert warned and all(r.levelno == logging.WARNING for r in warned)
    res = eng.run_round(0)
    assert res.backend == "einsum"


def test_on_mesh_backend_recorded_in_result(federation, mesh8, model):
    eng = _engine(federation, model, "quantized", mesh=mesh8, quant_hosts=4)
    eng.data, eng.states = shard_federation(federation, eng.states, mesh8)
    eng._ver_x, eng._ver_m = eng._verification_tensors()
    assert eng.agg_backend == "quantized"
    res = eng.run_round(0)
    assert res.backend == "quantized"


def test_auto_backend_resolves_via_plan(federation, mesh8, model):
    eng = _engine(federation, model, "auto", mesh=mesh8)
    eng.data, eng.states = shard_federation(federation, eng.states, mesh8)
    eng._ver_x, eng._ver_m = eng._verification_tensors()
    eff = eng.agg_backend
    assert eff in ("shard_map", "quantized")
    assert eng._merge_plan is not None
    assert eng._merge_plan["chosen"]["backend"] == eff
