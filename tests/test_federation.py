"""Federation-layer tests: aggregation properties, voting/quota semantics,
verification accept/reject logic, local-training behavior, full-round
integration on synthetic data (SURVEY.md §4 test plan)."""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from fedmse_tpu.config import CompatConfig, ExperimentConfig
from fedmse_tpu.data import build_dev_dataset, stack_clients, synthetic_clients
from fedmse_tpu.federation import (RoundEngine, elect_aggregator,
                                   init_client_states, make_aggregate_fn,
                                   make_local_train_all, make_mse_scores_fn,
                                   make_verify_fn)
from fedmse_tpu.models import make_model, init_stacked_params
from fedmse_tpu.utils.seeding import ExperimentRngs

DIM = 12


@pytest.fixture(scope="module")
def model():
    return make_model("hybrid", DIM, shrink_lambda=2.0)


@pytest.fixture(scope="module")
def stacked_params(model):
    return init_stacked_params(model, jax.random.key(1), 4)


# ---------------------------- aggregation ---------------------------- #

def test_fedavg_equal_weights_is_mean(model, stacked_params):
    """Property: FedAvg over the full cohort == plain mean (fed_avg with
    weight 1 per client, reference client_trainer.py:107-113)."""
    agg_fn = make_aggregate_fn(model, "avg")
    sel = jnp.ones(4)
    agg, w = agg_fn(stacked_params, sel, jnp.zeros((8, DIM)))
    np.testing.assert_allclose(np.asarray(w), 0.25, rtol=1e-6)
    want = jax.tree.map(lambda t: np.mean(np.asarray(t), axis=0), stacked_params)
    got = jax.tree.map(np.asarray, agg)
    for a, b in zip(jax.tree.leaves(want), jax.tree.leaves(got)):
        np.testing.assert_allclose(a, b, rtol=1e-5)


def test_fedavg_respects_selection_mask(model, stacked_params):
    agg_fn = make_aggregate_fn(model, "avg")
    sel = jnp.asarray([1.0, 0.0, 1.0, 0.0])
    agg, w = agg_fn(stacked_params, sel, jnp.zeros((8, DIM)))
    np.testing.assert_allclose(np.asarray(w), [0.5, 0, 0.5, 0], rtol=1e-6)
    leaf = jax.tree.leaves(stacked_params)[0]
    want = (np.asarray(leaf[0]) + np.asarray(leaf[2])) / 2
    np.testing.assert_allclose(np.asarray(jax.tree.leaves(agg)[0]), want, rtol=1e-5)


def test_fedprox_aggregation_equals_fedavg(model, stacked_params):
    """FedProx aggregation == FedAvg (reference client_trainer.py:132-134)."""
    sel = jnp.asarray([1.0, 1.0, 0.0, 1.0])
    dev = jnp.zeros((8, DIM))
    a1, w1 = make_aggregate_fn(model, "avg")(stacked_params, sel, dev)
    a2, w2 = make_aggregate_fn(model, "fedprox")(stacked_params, sel, dev)
    np.testing.assert_allclose(np.asarray(w1), np.asarray(w2))
    for x, y in zip(jax.tree.leaves(a1), jax.tree.leaves(a2)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y))


def test_mse_avg_weights_are_inverse_mse_normalized(model, stacked_params):
    """fed_mse_avg weight_i ∝ 1/MSE(dev, recon_i), summing to 1
    (reference client_trainer.py:115-130)."""
    from fedmse_tpu.ops.losses import mse_loss
    rng = np.random.default_rng(0)
    dev = jnp.asarray(rng.normal(size=(32, DIM)).astype(np.float32))
    sel = jnp.ones(4)
    agg_fn = make_aggregate_fn(model, "mse_avg")
    _, w = agg_fn(stacked_params, sel, dev)
    mses = []
    for i in range(4):
        p_i = jax.tree.map(lambda t: t[i], stacked_params)
        _, recon = model.apply({"params": p_i}, dev)
        mses.append(float(mse_loss(dev, recon)))
    want = (1.0 / np.asarray(mses))
    want = want / want.sum()
    np.testing.assert_allclose(np.asarray(w), want, rtol=1e-4)
    assert float(jnp.sum(w)) == pytest.approx(1.0, abs=1e-5)


# ------------------------------ voting ------------------------------- #

def test_mse_scores_restandardize_matches_torch_convention(model, stacked_params):
    """calculate_mse_score re-standardizes with ddof=1 + 1e-8 then averages
    batch MSEs (reference client_trainer.py:208-247)."""
    rng = np.random.default_rng(1)
    val = rng.normal(size=(300, DIM)).astype(np.float32)
    scores_fn = make_mse_scores_fn(model, restandardize=True, tie_break=False)
    got = np.asarray(scores_fn(stacked_params, jnp.asarray(val),
                               jnp.ones(300), jax.random.key(0)))
    # manual reference computation for client 0
    mean = val.mean(0, keepdims=True)
    std = val.std(0, ddof=1, keepdims=True) + 1e-8
    norm = (val - mean) / std
    p0 = jax.tree.map(lambda t: t[0], stacked_params)
    batch_mses = []
    for i in range(0, 300, 128):
        b = jnp.asarray(norm[i:i + 128])
        _, recon = model.apply({"params": p0}, b)
        batch_mses.append(float(jnp.mean(jnp.square(b - recon))))
    assert got[0] == pytest.approx(np.mean(batch_mses), rel=1e-4)


def test_tie_break_factor_bounds(model, stacked_params):
    rng = np.random.default_rng(1)
    val = jnp.asarray(rng.normal(size=(64, DIM)).astype(np.float32))
    m = jnp.ones(64)
    base = np.asarray(make_mse_scores_fn(model, tie_break=False)(
        stacked_params, val, m, jax.random.key(0)))
    jittered = np.asarray(make_mse_scores_fn(model, tie_break=True)(
        stacked_params, val, m, jax.random.key(0)))
    ratio = jittered / base
    assert np.all(ratio >= 1 - 1.01e-4) and np.all(ratio <= 1 + 1.01e-4)
    assert not np.allclose(ratio, 1.0)


def test_election_first_voter_wins_and_quota():
    """Voter 0 votes for the lowest-MSE other client under quota
    (reference client_trainer.py:249-285, main.py:282-288)."""
    votes = np.zeros(4, dtype=np.int64)
    scores = np.asarray([0.5, 0.1, 0.3, 0.2])
    agg_count = np.zeros(4, dtype=np.int64)
    winner, _ = elect_aggregator([0, 1, 2, 3], lambda: scores, agg_count, votes)
    assert winner == 1 and votes[1] == 1  # lowest MSE, not the voter itself

    # quota: client 1 maxed out -> next lowest (3) wins
    agg_count = np.asarray([0, 3, 0, 0])
    winner, _ = elect_aggregator([0, 1, 2, 3], lambda: scores, agg_count, votes)
    assert winner == 3

    # voter never votes for itself even if it has the lowest score
    winner, _ = elect_aggregator([1, 0, 2, 3], lambda: scores,
                                 np.zeros(4, dtype=np.int64), votes)
    assert winner == 3  # 1 is the voter; best other under quota is 3 (0.2)

    # all candidates at quota -> None
    winner, _ = elect_aggregator([0, 1], lambda: scores,
                                 np.asarray([3, 3]), votes)
    assert winner is None


# --------------------------- verification ---------------------------- #

def _mk_states(model, n=4, seed=2):
    tx = optax.adam(1e-3)
    return init_client_states(model, tx, jax.random.key(seed), n)


def test_verify_first_update_always_accepted(model):
    states = _mk_states(model)
    verify = make_verify_fn(model, verification_threshold=0.0,
                            performance_threshold=0.0)
    agg = jax.tree.map(lambda t: t[0] + 100.0, states.params)  # huge delta
    ver_x = jnp.zeros((4, 16, DIM))
    ver_m = jnp.ones((4, 16))
    onehot = jnp.asarray([0.0, 0, 0, 1])  # client 3 aggregates
    out = verify(states, agg, ver_x, ver_m, onehot, jnp.ones(4))
    acc = np.asarray(out.accepted)
    assert acc.tolist() == [True, True, True, True]  # first contact + aggregator
    assert np.asarray(out.states.rejected).tolist() == [0, 0, 0, 0]
    assert np.asarray(out.states.hist_seen).tolist() == [True, True, True, False]


def test_verify_reject_on_param_delta(model):
    states = _mk_states(model)
    verify = make_verify_fn(model, verification_threshold=3.0,
                            performance_threshold=0.002)
    ver_x = jnp.zeros((4, 16, DIM))
    ver_m = jnp.ones((4, 16))
    onehot = jnp.asarray([0.0, 0, 0, 1])
    agg1 = jax.tree.map(lambda t: t[0], states.params)
    out1 = verify(states, agg1, ver_x, ver_m, onehot, jnp.ones(4))
    # second update with a huge parameter jump -> delta check fails
    agg2 = jax.tree.map(lambda t: t + 50.0, agg1)
    out2 = verify(out1.states, agg2, ver_x, ver_m, onehot, jnp.ones(4))
    acc = np.asarray(out2.accepted)
    assert acc.tolist() == [False, False, False, True]  # only aggregator
    assert np.asarray(out2.states.rejected).tolist() == [1, 1, 1, 0]
    assert np.all(np.asarray(out2.param_delta)[:3] > 3.0)
    # history advanced to the REJECTED state (model_verifier.py:59-66)
    h = np.asarray(jax.tree.leaves(out2.states.hist_params)[0][0])
    w = np.asarray(jax.tree.leaves(agg2)[0])
    np.testing.assert_allclose(h, w)
    # rejection does not move the client's live params
    p = np.asarray(jax.tree.leaves(out2.states.params)[0][0])
    p_prev = np.asarray(jax.tree.leaves(out1.states.params)[0][0])
    np.testing.assert_allclose(p, p_prev)


def test_verify_reject_on_perf_drop(model):
    states = _mk_states(model)
    verify = make_verify_fn(model, verification_threshold=1e9,
                            performance_threshold=0.002)
    rng = np.random.default_rng(3)
    ver_x = jnp.asarray(np.tile(rng.normal(size=(1, 16, DIM)), (4, 1, 1))
                        .astype(np.float32))
    ver_m = jnp.ones((4, 16))
    onehot = jnp.asarray([0.0, 0, 0, 1])
    agg1 = jax.tree.map(lambda t: t[0], states.params)
    out1 = verify(states, agg1, ver_x, ver_m, onehot, jnp.ones(4))
    # corrupt the decoder output layer -> reconstruction collapses -> perf drop
    agg2 = jax.tree.map(lambda t: t * 0.0 + 10.0, agg1)
    out2 = verify(out1.states, agg2, ver_x, ver_m, onehot, jnp.ones(4))
    assert np.asarray(out2.accepted).tolist() == [False, False, False, True]
    assert np.all(np.asarray(out2.perf_change)[:3] < -0.002)


def test_verify_default_mode_has_the_history_poisoning_hole(model):
    """Reference-faithful mode accepts a zeroed broadcast forever once it
    gets in: first contact is unconditional (model_verifier.py:41-47) and
    history updates every attempt (:59-66), so round 2's zero model sees
    delta=0 / perf_change=0 vs the poisoned history. Measured live in
    ATTACK_r04.json (accept 0.857, AUC 0.5, never flagged). This test pins
    the hole so the hardened mode's fix is provably a behavior CHANGE."""
    states = _mk_states(model)
    verify = make_verify_fn(model, verification_threshold=3.0,
                            performance_threshold=0.002, hardened=False)
    rng = np.random.default_rng(7)
    ver_x = jnp.asarray(rng.normal(size=(4, 16, DIM)).astype(np.float32))
    ver_m = jnp.ones((4, 16))
    onehot = jnp.asarray([0.0, 0, 0, 1])
    zero = jax.tree.map(lambda t: jnp.zeros_like(t[0]), states.params)
    out1 = verify(states, zero, ver_x, ver_m, onehot, jnp.ones(4))
    assert np.asarray(out1.accepted).tolist() == [True] * 4  # first contact
    out2 = verify(out1.states, zero, ver_x, ver_m, onehot, jnp.ones(4))
    assert np.asarray(out2.accepted).tolist() == [True] * 4  # the hole
    assert np.asarray(out2.states.rejected).tolist() == [0, 0, 0, 0]


def _trained_params(model, x, steps=300, lr=1e-2, seed=5):
    """A genuinely trained single param set (reconstructs x well) — the
    hardened verifier's own-model baselines only mean something when the
    own model works, as trained client models do."""
    params = model.init(jax.random.key(seed), x)["params"]
    tx = optax.adam(lr)
    opt = tx.init(params)

    @jax.jit
    def step(p, o):
        def loss_fn(q):
            _, recon = model.apply({"params": q}, x)
            return jnp.mean((recon - x) ** 2)
        g = jax.grad(loss_fn)(p)
        up, o2 = tx.update(g, o, p)
        return optax.apply_updates(p, up), o2

    for _ in range(steps):
        params, opt = step(params, opt)
    return params


def test_verify_hardened_blocks_zero_attack_and_flags(model):
    """Hardened mode measures both gates against the client's OWN current
    model: the zeroed broadcast scores far below any trained model, is
    rejected from FIRST contact (no unconditional accept to exploit),
    keeps being rejected (no baseline to poison), and the rejected
    counter reaches the possible-attack flag threshold (3)."""
    rng = np.random.default_rng(7)
    xv = jnp.asarray(rng.normal(size=(16, DIM)).astype(np.float32))
    trained = _trained_params(model, xv)
    states = _mk_states(model)
    states = dataclasses.replace(
        states, params=jax.tree.map(
            lambda t: jnp.broadcast_to(t, (4,) + t.shape), trained))
    verify = make_verify_fn(model, verification_threshold=3.0,
                            performance_threshold=0.002, hardened=True)
    ver_x = jnp.broadcast_to(xv, (4,) + xv.shape)
    ver_m = jnp.ones((4, 16))
    onehot = jnp.asarray([0.0, 0, 0, 1])
    zero = jax.tree.map(lambda t: jnp.zeros_like(t[0]), states.params)
    out = verify(states, zero, ver_x, ver_m, onehot, jnp.ones(4))
    for _ in range(2):
        assert np.asarray(out.accepted).tolist() == [False, False, False, True]
        out = verify(out.states, zero, ver_x, ver_m, onehot, jnp.ones(4))
    # live params never took the zero state (check every leaf: the first
    # is a zero-init bias even in a healthy model)
    assert max(float(np.abs(np.asarray(leaf[:3])).max())
               for leaf in jax.tree.leaves(out.states.params)) > 0.0
    # three consecutive rejections -> possible-attack threshold reached
    assert np.asarray(out.states.rejected).tolist() == [3, 3, 3, 0]


def test_verify_hardened_recovery_path(model):
    """A client whose state was trashed while it served as aggregator
    (the aggregator loads the broadcast unconditionally,
    client_trainer.py:333) must be able to rejoin: an honest broadcast
    that strictly improves on its ruined own model is accepted even
    though the Frobenius delta from zero to a trained model far exceeds
    the step-size cap — the IMPROVES waiver, not first contact."""
    rng = np.random.default_rng(11)
    xv = jnp.asarray(rng.normal(size=(16, DIM)).astype(np.float32))
    trained = _trained_params(model, xv)
    states = _mk_states(model)
    params = jax.tree.map(
        lambda t: jnp.broadcast_to(t, (4,) + t.shape), trained)
    params = jax.tree.map(lambda t: t.at[0].set(0.0), params)
    states = dataclasses.replace(
        states, params=params,
        hist_seen=jnp.asarray([True, True, True, True]))
    verify = make_verify_fn(model, verification_threshold=3.0,
                            performance_threshold=0.002, hardened=True)
    ver_x = jnp.broadcast_to(xv, (4,) + xv.shape)
    ver_m = jnp.ones((4, 16))
    onehot = jnp.asarray([0.0, 0, 0, 1])
    out = verify(states, trained, ver_x, ver_m, onehot, jnp.ones(4))
    assert np.asarray(out.accepted).tolist() == [True, True, True, True]
    # client 0's live params actually recovered to the broadcast
    l0 = jax.tree.leaves(out.states.params)
    lt = jax.tree.leaves(trained)
    np.testing.assert_allclose(np.asarray(l0[-1][0]), np.asarray(lt[-1]),
                               rtol=1e-6)


def test_verify_hardened_recovery_waiver_is_delta_capped(model):
    """ADVICE r5 #1: the recovery waiver WIDENS the Frobenius step cap
    (recovery_delta_cap, default 10x verification_threshold), it does not
    lift it. The same trashed-aggregator scenario the recovery path
    accepts under the default ceiling must be rejected when the ceiling
    sits below the broadcast's delta — a big perf improvement alone no
    longer buys an arbitrarily large parameter step."""
    rng = np.random.default_rng(11)
    xv = jnp.asarray(rng.normal(size=(16, DIM)).astype(np.float32))
    trained = _trained_params(model, xv)
    states = _mk_states(model)
    params = jax.tree.map(
        lambda t: jnp.broadcast_to(t, (4,) + t.shape), trained)
    params = jax.tree.map(lambda t: t.at[0].set(0.0), params)
    states = dataclasses.replace(
        states, params=params,
        hist_seen=jnp.asarray([True, True, True, True]))
    ver_x = jnp.broadcast_to(xv, (4,) + xv.shape)
    ver_m = jnp.ones((4, 16))
    onehot = jnp.asarray([0.0, 0, 0, 1])

    # ceiling below the zero->trained distance (~19): client 0's recovery
    # is refused even though its perf improves far beyond the margin ...
    tight = make_verify_fn(model, verification_threshold=3.0,
                           performance_threshold=0.002, hardened=True,
                           recovery_delta_cap=1.0)
    out = tight(states, trained, ver_x, ver_m, onehot, jnp.ones(4))
    assert np.asarray(out.perf_change)[0] > 0.1  # waiver precondition held
    assert np.asarray(out.param_delta)[0] > 1.0
    assert np.asarray(out.accepted).tolist() == [False, True, True, True]

    # ... while the default ceiling (10x threshold = 30) clears it
    default = make_verify_fn(model, verification_threshold=3.0,
                             performance_threshold=0.002, hardened=True)
    out2 = default(states, trained, ver_x, ver_m, onehot, jnp.ones(4))
    assert np.asarray(out2.param_delta)[0] < 30.0
    assert np.asarray(out2.accepted).tolist() == [True, True, True, True]


def test_verify_hardened_marginal_improvement_does_not_waive_cap(model):
    """The recovery waiver requires a LARGE improvement (recovery_threshold,
    default 0.1), not the 0.002 noise threshold: a far-away model that
    merely edges out the client's own model must still fail the Frobenius
    step-size cap (round-5 review: otherwise any perf-improving broadcast
    gets an unbounded step and the cap is decorative)."""
    rng = np.random.default_rng(13)
    xv = jnp.asarray(rng.normal(size=(16, DIM)).astype(np.float32))
    own = _trained_params(model, xv, steps=300, seed=5)
    # independently initialized, trained longer: slightly better perf
    # (well under +0.1), but Frobenius-far from `own`
    other = _trained_params(model, xv, steps=600, seed=6)
    states = _mk_states(model)
    states = dataclasses.replace(
        states,
        params=jax.tree.map(
            lambda t: jnp.broadcast_to(t, (4,) + t.shape), own),
        hist_seen=jnp.asarray([True, True, True, True]))
    verify = make_verify_fn(model, verification_threshold=3.0,
                            performance_threshold=0.002, hardened=True)
    ver_x = jnp.broadcast_to(xv, (4,) + xv.shape)
    ver_m = jnp.ones((4, 16))
    onehot = jnp.asarray([0.0, 0, 0, 1])
    out = verify(states, other, ver_x, ver_m, onehot, jnp.ones(4))
    delta = np.asarray(out.param_delta)
    change = np.asarray(out.perf_change)
    # preconditions that make this test meaningful
    assert np.all(delta[:3] > 3.0), delta
    assert np.all(change[:3] < 0.1), change
    # marginal improvement + far params -> rejected (aggregator exempt)
    assert np.asarray(out.accepted).tolist() == [False, False, False, True]


def test_verify_hardened_accepts_honest_aggregate(model):
    """The hardened rule must not burn honest federation. Post-broadcast,
    honest clients share the global model plus small local-training
    deltas, and the next honest aggregate is near them: small Frobenius
    delta, comparable performance -> accepted, from first contact onward
    (hardened mode has no first-contact exemption to lean on)."""
    states = _mk_states(model)
    common = jax.tree.map(lambda t: t[:1], states.params)  # one shared init
    jitter = jax.tree.map(  # per-client local-training drift, tiny
        lambda t: t * 0.01, states.params)
    states = dataclasses.replace(
        states, params=jax.tree.map(
            lambda c, j: jnp.broadcast_to(c, j.shape) + j, common, jitter))
    verify = make_verify_fn(model, verification_threshold=3.0,
                            performance_threshold=0.002, hardened=True)
    rng = np.random.default_rng(9)
    ver_x = jnp.asarray(rng.normal(size=(4, 16, DIM)).astype(np.float32))
    ver_m = jnp.ones((4, 16))
    onehot = jnp.asarray([0.0, 0, 0, 1])
    # honest aggregate: the mean of the clients' current models
    agg = jax.tree.map(lambda t: t.mean(axis=0), states.params)
    out1 = verify(states, agg, ver_x, ver_m, onehot, jnp.ones(4))
    assert np.asarray(out1.accepted).tolist() == [True] * 4
    out2 = verify(out1.states, agg, ver_x, ver_m, onehot, jnp.ones(4))
    assert np.asarray(out2.accepted).tolist() == [True] * 4
    assert np.asarray(out2.states.rejected).tolist() == [0, 0, 0, 0]


# ------------------------- local training ---------------------------- #

def test_local_training_decreases_loss(model):
    tx = optax.adam(1e-2)
    train_all = make_local_train_all(model, tx, epochs=8, patience=8,
                                     fedprox=False, mu=0.0, donate=False)
    states = _mk_states(model, n=2)
    rng = np.random.default_rng(4)
    xb = jnp.asarray(rng.normal(size=(2, 6, 8, DIM)).astype(np.float32))
    mb = jnp.ones((2, 6, 8))
    sel = jnp.ones(2)
    _, _, _, _, tracking = train_all(states.params, states.opt_state,
                                     states.prev_global, sel, xb, mb, xb, mb)
    track = np.asarray(tracking)
    assert np.all(track[:, -1, 0] < track[:, 0, 0])  # train loss decreased


def test_unselected_clients_unchanged(model):
    tx = optax.adam(1e-2)
    train_all = make_local_train_all(model, tx, epochs=2, patience=2,
                                     fedprox=False, mu=0.0, donate=False)
    states = _mk_states(model, n=2)
    rng = np.random.default_rng(5)
    xb = jnp.asarray(rng.normal(size=(2, 4, 8, DIM)).astype(np.float32))
    mb = jnp.ones((2, 4, 8))
    sel = jnp.asarray([1.0, 0.0])
    params, _, _, min_valid, tracking = train_all(
        states.params, states.opt_state, states.prev_global, sel, xb, mb, xb, mb)
    before = np.asarray(jax.tree.leaves(states.params)[0][1])
    after = np.asarray(jax.tree.leaves(params)[0][1])
    np.testing.assert_allclose(before, after)  # client 1 untouched
    assert not np.allclose(np.asarray(jax.tree.leaves(params)[0][0]),
                           np.asarray(jax.tree.leaves(states.params)[0][0]))
    # unselected clients report no training curves (NaN-masked)
    assert np.all(np.isnan(np.asarray(tracking)[1]))
    assert np.isnan(np.asarray(min_valid)[1])


def test_compact_aggregate_matches_dense(model):
    """fed_mse_avg with sel_idx scores only the cohort; weights and the
    aggregated model must equal the dense scoring path exactly."""
    agg = make_aggregate_fn(model, "mse_avg")
    states = _mk_states(model, n=4)
    rng = np.random.default_rng(12)
    dev = jnp.asarray(rng.normal(size=(20, DIM)).astype(np.float32))
    sel = jnp.asarray([1.0, 0.0, 1.0, 0.0])
    p_d, w_d = agg(states.params, sel, dev)
    p_c, w_c = agg(states.params, sel, dev,
                   sel_idx=jnp.asarray([0, 2], jnp.int32))
    np.testing.assert_allclose(np.asarray(w_d), np.asarray(w_c), atol=1e-7)
    for d, c in zip(jax.tree.leaves(p_d), jax.tree.leaves(p_c)):
        np.testing.assert_allclose(np.asarray(d), np.asarray(c), atol=1e-7)


def test_compact_cohort_matches_dense(model):
    """sel_idx gather->train->scatter must reproduce the dense masked path
    exactly: same trained params/opt for the cohort, untouched state and
    NaN curves for the rest (local_training.make_local_train_all)."""
    tx = optax.adam(1e-2)
    train_all = make_local_train_all(model, tx, epochs=3, patience=3,
                                     fedprox=False, mu=0.0, donate=False)
    states = _mk_states(model, n=4)
    rng = np.random.default_rng(11)
    xb = jnp.asarray(rng.normal(size=(4, 5, 8, DIM)).astype(np.float32))
    mb = jnp.ones((4, 5, 8))
    sel = jnp.asarray([1.0, 0.0, 1.0, 0.0])
    args = (states.params, states.opt_state, states.prev_global, sel,
            xb, mb, xb, mb)
    dense = train_all(*args)
    compact = train_all(*args, sel_idx=jnp.asarray([0, 2], jnp.int32))
    for out in (0, 1, 2):  # params, opt_state, best_params
        for d, c in zip(jax.tree.leaves(dense[out]),
                        jax.tree.leaves(compact[out])):
            np.testing.assert_allclose(np.asarray(d), np.asarray(c),
                                       atol=1e-6)
    np.testing.assert_allclose(np.asarray(dense[3]), np.asarray(compact[3]),
                               atol=1e-6)  # min_valid incl. NaN slots
    np.testing.assert_allclose(np.asarray(dense[4]), np.asarray(compact[4]),
                               atol=1e-6)  # tracking incl. NaN rows


def test_early_stopping_freezes_params(model):
    """With patience=1 and a validation set the model can't improve on
    (constant zeros after convergence), later epochs must be no-ops."""
    tx = optax.adam(1e-2)
    train_all = make_local_train_all(model, tx, epochs=6, patience=1,
                                     fedprox=False, mu=0.0, donate=False)
    states = _mk_states(model, n=1)
    rng = np.random.default_rng(6)
    xb = jnp.asarray(rng.normal(size=(1, 3, 8, DIM)).astype(np.float32))
    mb = jnp.ones((1, 3, 8))
    # validation loss will plateau quickly on random data with tiny lr
    _, _, _, _, tracking = train_all(states.params, states.opt_state,
                                     states.prev_global, jnp.ones(1),
                                     xb, mb, xb, mb)
    track = np.asarray(tracking)[0]  # [E, 3]
    active = track[:, 2]
    # once inactive, stays inactive
    first_inactive = np.argmin(active) if np.any(active == 0) else len(active)
    assert np.all(active[first_inactive:] == 0)


def test_fedprox_prox_term_changes_training(model):
    tx = optax.adam(1e-2)
    states = _mk_states(model, n=1)
    rng = np.random.default_rng(7)
    xb = jnp.asarray(rng.normal(size=(1, 3, 8, DIM)).astype(np.float32))
    mb = jnp.ones((1, 3, 8))
    kw = dict(epochs=3, patience=3, donate=False)
    p1, *_ = make_local_train_all(model, tx, fedprox=False, mu=0.0, **kw)(
        states.params, states.opt_state, states.prev_global, jnp.ones(1),
        xb, mb, xb, mb)
    p2, *_ = make_local_train_all(model, tx, fedprox=True, mu=10.0, **kw)(
        states.params, states.opt_state, states.prev_global, jnp.ones(1),
        xb, mb, xb, mb)
    l1 = np.asarray(jax.tree.leaves(p1)[0])
    l2 = np.asarray(jax.tree.leaves(p2)[0])
    assert not np.allclose(l1, l2)
    # strong prox pulls params toward prev_global (the init)
    init = np.asarray(jax.tree.leaves(states.prev_global)[0])
    assert np.linalg.norm(l2 - init) < np.linalg.norm(l1 - init)


# --------------------------- integration ----------------------------- #

@pytest.mark.parametrize("model_type,update_type",
                         [("hybrid", "mse_avg"), ("autoencoder", "avg"),
                          ("hybrid", "fedprox")])
def test_full_round_integration(model_type, update_type):
    cfg = ExperimentConfig(dim_features=DIM, network_size=4, epochs=2,
                           batch_size=8)
    clients = synthetic_clients(n_clients=4, dim=DIM, n_normal=120,
                                n_abnormal=60)
    rngs = ExperimentRngs(run=0)
    dev_x = build_dev_dataset(clients, rngs.data_rng)
    data = stack_clients(clients, dev_x, cfg.batch_size)
    m = make_model(model_type, DIM, shrink_lambda=cfg.shrink_lambda)
    eng = RoundEngine(m, cfg, data, n_real=4, rngs=rngs,
                      model_type=model_type, update_type=update_type)
    for r in range(2):
        res = eng.run_round(r)
    assert res.client_metrics.shape == (4,)
    assert np.all(res.client_metrics > 0.5)  # anomalies are separable
    assert res.aggregator in res.selected
    assert eng.host.aggregation_count.sum() == 2


def test_round_with_padded_clients_matches_unpadded():
    """Padding the client axis must not change real clients' results."""
    cfg = ExperimentConfig(dim_features=DIM, network_size=4, epochs=2,
                           batch_size=8,
                           compat=CompatConfig(vote_tie_break=False))
    clients = synthetic_clients(n_clients=4, dim=DIM, n_normal=120,
                                n_abnormal=60)
    res = {}
    for pad in (4, 8):
        rngs = ExperimentRngs(run=0)
        dev_x = build_dev_dataset(clients, rngs.data_rng)
        data = stack_clients(clients, dev_x, cfg.batch_size, pad_clients_to=pad)
        m = make_model("hybrid", DIM, shrink_lambda=cfg.shrink_lambda)
        eng = RoundEngine(m, cfg, data, n_real=4, rngs=ExperimentRngs(run=0),
                          model_type="hybrid", update_type="mse_avg")
        r = eng.run_round(0, selected=[0, 2])
        res[pad] = r
    np.testing.assert_allclose(res[4].client_metrics, res[8].client_metrics,
                               atol=2e-3)
    assert res[4].aggregator == res[8].aggregator