"""Driver + checkpointing integration: the full CLI pipeline on tiny CSV
shards, reference artifact layout, and kill/resume (SURVEY.md §4, §5.4)."""

import glob
import json
import os
import pickle

import numpy as np
import pandas as pd
import pytest

from fedmse_tpu.config import DatasetConfig, ExperimentConfig
from fedmse_tpu.main import main as cli_main
from tests.test_data import _write_client_csvs

DIM = 6


@pytest.fixture(scope="module")
def dataset_dir(tmp_path_factory):
    root = tmp_path_factory.mktemp("shards")
    _write_client_csvs(str(root), 4, dim=DIM, n_normal=80, n_abnormal=30)
    cfg_path = root / "config.json"
    ds = DatasetConfig.for_client_dirs(str(root), 4)
    with open(cfg_path, "w") as f:
        json.dump(ds.to_json(), f)
    return str(root), str(cfg_path)


def test_cli_end_to_end_artifacts(dataset_dir, tmp_path):
    root, cfg_path = dataset_dir
    ckpt = str(tmp_path / "ckpt")
    out = cli_main([
        "--dataset-config", cfg_path,
        "--model-types", "hybrid", "--update-types", "mse_avg,avg",
        "--network-size", "4", "--dim-features", str(DIM),
        "--epochs", "2", "--num-rounds", "2", "--batch-size", "8",
        "--num-participants", "0.5",
        "--checkpoint-dir", ckpt,
        "--experiment-name", "t1",
    ])
    best = out["best_metrics"]["hybrid"]
    assert best["mse_avg"] > 0.6 and best["avg"] > 0.6

    # reference layout (src/main.py:342-355, 390-399; client_trainer.py:337-350)
    results = glob.glob(os.path.join(
        ckpt, "Results", "Update", "4", "t1", "Run_0", "AUC", "*.json"))
    assert len(results) == 2
    rows = [json.loads(l) for l in open(results[0])]
    assert rows[0]["round"] == 1 and len(rows[0]["client_metrics"]) == 4
    assert "global_loss" in rows[0]

    summary = json.load(open(os.path.join(
        ckpt, "Results", "Update", "4", "t1", "training_summary.json")))
    assert summary["network_size"] == 4
    assert summary["metric_type"] == "AUC"

    model_files = glob.glob(os.path.join(
        ckpt, "4", "t1", "0", "ClientModel", "FL-IoT", "hybrid", "*",
        "Client-*", "model.npz"))
    assert len(model_files) == 8  # 4 clients x 2 update types
    arrs = np.load(model_files[0])
    assert len(arrs.files) == 8  # 4 dense layers x (kernel, bias)

    tracking_files = glob.glob(os.path.join(
        ckpt, "4", "t1", "0", "ClientModel", "FL-IoT", "hybrid", "*",
        "Client-*", "training_tracking.pkl"))
    rows = pickle.load(open(tracking_files[0], "rb"))
    assert all(len(r) == 2 for r in rows)  # (train_loss, valid_loss)

    verif = os.path.join(ckpt, "Results", "Update", "4", "t1", "Run_0",
                         "verification_results.json")
    if os.path.exists(verif):  # written only in rounds with an aggregator
        vrows = [json.loads(l) for l in open(verif)]
        assert {"client_id", "rejected_updates", "is_verified"} <= \
            set(vrows[0]["verification_results"][0])


def test_resume_continues_rounds(dataset_dir, tmp_path):
    root, cfg_path = dataset_dir
    common = [
        "--dataset-config", cfg_path,
        "--model-types", "hybrid", "--update-types", "avg",
        "--network-size", "4", "--dim-features", str(DIM),
        "--epochs", "1", "--batch-size", "8", "--no-save",
        "--checkpoint-dir", str(tmp_path / "c"),
        "--resume-dir", str(tmp_path / "r"),
        "--experiment-name", "t2",
    ]
    cli_main(common + ["--num-rounds", "1"])
    out = cli_main(common + ["--num-rounds", "3"])
    times = out["results"]["hybrid/avg/run0"]["round_times"]
    assert len(times) == 2  # rounds 2..3 only — round 1 was resumed, not re-run


def test_global_early_stop_inverted_compat(dataset_dir, tmp_path):
    """Compat quirk 10: with AUC improving, min(metrics) rarely decreases, so
    the inverted comparison stops after patience+1 stagnant rounds."""
    root, cfg_path = dataset_dir
    out = cli_main([
        "--dataset-config", cfg_path,
        "--model-types", "hybrid", "--update-types", "avg",
        "--network-size", "4", "--dim-features", str(DIM),
        "--epochs", "1", "--num-rounds", "8", "--batch-size", "8",
        "--no-save", "--checkpoint-dir", str(tmp_path / "c2"),
        "--experiment-name", "t3",
    ])
    assert out["results"]["hybrid/avg/run0"]["round_times"], "ran at least 1 round"
    # it must have stopped early at SOME point under the inverted rule
    assert len(out["results"]["hybrid/avg/run0"]["round_times"]) <= 8
