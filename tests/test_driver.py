"""Driver + checkpointing integration: the full CLI pipeline on tiny CSV
shards, reference artifact layout, and kill/resume (SURVEY.md §4, §5.4)."""

import glob
import json
import os
import pickle

import numpy as np
import pandas as pd
import pytest

from fedmse_tpu.config import DatasetConfig, ExperimentConfig
from fedmse_tpu.main import main as cli_main
from tests.test_data import _write_client_csvs

DIM = 6


@pytest.fixture(scope="module")
def dataset_dir(tmp_path_factory):
    root = tmp_path_factory.mktemp("shards")
    _write_client_csvs(str(root), 4, dim=DIM, n_normal=80, n_abnormal=30)
    cfg_path = root / "config.json"
    ds = DatasetConfig.for_client_dirs(str(root), 4)
    with open(cfg_path, "w") as f:
        json.dump(ds.to_json(), f)
    return str(root), str(cfg_path)


def test_cli_end_to_end_artifacts(dataset_dir, tmp_path):
    root, cfg_path = dataset_dir
    ckpt = str(tmp_path / "ckpt")
    out = cli_main([
        "--dataset-config", cfg_path,
        "--model-types", "hybrid", "--update-types", "mse_avg,avg",
        "--network-size", "4", "--dim-features", str(DIM),
        "--epochs", "2", "--num-rounds", "2", "--batch-size", "8",
        "--num-participants", "0.5",
        "--checkpoint-dir", ckpt,
        "--experiment-name", "t1",
    ])
    best = out["best_metrics"]["hybrid"]
    assert best["mse_avg"] > 0.6 and best["avg"] > 0.6

    # reference layout (src/main.py:342-355, 390-399; client_trainer.py:337-350)
    results = glob.glob(os.path.join(
        ckpt, "Results", "Update", "4", "t1", "Run_0", "AUC", "*.json"))
    assert len(results) == 2
    rows = [json.loads(l) for l in open(results[0])]
    assert rows[0]["round"] == 1 and len(rows[0]["client_metrics"]) == 4
    assert "global_loss" in rows[0]

    summary = json.load(open(os.path.join(
        ckpt, "Results", "Update", "4", "t1", "training_summary.json")))
    assert summary["network_size"] == 4
    assert summary["metric_type"] == "AUC"

    model_files = glob.glob(os.path.join(
        ckpt, "4", "t1", "0", "ClientModel", "FL-IoT", "hybrid", "*",
        "Client-*", "model.npz"))
    assert len(model_files) == 8  # 4 clients x 2 update types
    arrs = np.load(model_files[0])
    assert len(arrs.files) == 8  # 4 dense layers x (kernel, bias)

    tracking_files = glob.glob(os.path.join(
        ckpt, "4", "t1", "0", "ClientModel", "FL-IoT", "hybrid", "*",
        "Client-*", "training_tracking.pkl"))
    rows = pickle.load(open(tracking_files[0], "rb"))
    assert all(len(r) == 2 for r in rows)  # (train_loss, valid_loss)

    verif = os.path.join(ckpt, "Results", "Update", "4", "t1", "Run_0",
                         "verification_results.json")
    if os.path.exists(verif):  # written only in rounds with an aggregator
        vrows = [json.loads(l) for l in open(verif)]
        assert {"client_id", "rejected_updates", "is_verified"} <= \
            set(vrows[0]["verification_results"][0])


def test_resume_continues_rounds(dataset_dir, tmp_path):
    root, cfg_path = dataset_dir
    common = [
        "--dataset-config", cfg_path,
        "--model-types", "hybrid", "--update-types", "avg",
        "--network-size", "4", "--dim-features", str(DIM),
        "--epochs", "1", "--batch-size", "8", "--no-save",
        "--checkpoint-dir", str(tmp_path / "c"),
        "--resume-dir", str(tmp_path / "r"),
        "--experiment-name", "t2",
    ]
    cli_main(common + ["--num-rounds", "1"])
    out = cli_main(common + ["--num-rounds", "3"])
    times = out["results"]["hybrid/avg/run0"]["round_times"]
    assert len(times) == 2  # rounds 2..3 only — round 1 was resumed, not re-run


def test_resume_dir_pipeline_fallback_warns_and_runs_serial(
        dataset_dir, tmp_path, monkeypatch):
    """--resume-dir silently forced the serial chunk loop; now it must
    WARN naming both flags, and the fallback itself is pinned: the
    pipelined executor is replaced with a tripwire, so the run completing
    proves the serial path ran."""
    import logging

    from fedmse_tpu.federation import pipeline as pipeline_mod

    def tripwire(*a, **k):
        raise AssertionError(
            "run_pipelined_schedule must not run under --resume-dir")

    monkeypatch.setattr(pipeline_mod, "run_pipelined_schedule", tripwire)

    class Capture(logging.Handler):
        # package logger is propagate=False (utils/logging.py): caplog
        # never sees it, attach directly (test_shard_native idiom)
        def __init__(self):
            super().__init__(logging.WARNING)
            self.records = []

        def emit(self, record):
            self.records.append(record)

    root, cfg_path = dataset_dir
    pkg = logging.getLogger("fedmse_tpu")
    handler = Capture()
    pkg.addHandler(handler)
    try:
        out = cli_main([
            "--dataset-config", cfg_path,
            "--model-types", "hybrid", "--update-types", "avg",
            "--network-size", "4", "--dim-features", str(DIM),
            "--epochs", "1", "--num-rounds", "2", "--batch-size", "8",
            "--no-save", "--checkpoint-dir", str(tmp_path / "c"),
            "--resume-dir", str(tmp_path / "r"),
            "--experiment-name", "tw",
        ])
    finally:
        pkg.removeHandler(handler)
    assert out["results"]["hybrid/avg/run0"]["round_times"]
    warnings = [r.getMessage() for r in handler.records]
    assert any("--resume-dir" in w and "fused_pipeline" in w
               for w in warnings), warnings


def test_global_early_stop_inverted_compat(dataset_dir, tmp_path):
    """Compat quirk 10: with AUC improving, min(metrics) rarely decreases, so
    the inverted comparison stops after patience+1 stagnant rounds."""
    root, cfg_path = dataset_dir
    out = cli_main([
        "--dataset-config", cfg_path,
        "--model-types", "hybrid", "--update-types", "avg",
        "--network-size", "4", "--dim-features", str(DIM),
        "--epochs", "1", "--num-rounds", "8", "--batch-size", "8",
        "--no-save", "--checkpoint-dir", str(tmp_path / "c2"),
        "--experiment-name", "t3",
    ])
    assert out["results"]["hybrid/avg/run0"]["round_times"], "ran at least 1 round"
    # it must have stopped early at SOME point under the inverted rule
    assert len(out["results"]["hybrid/avg/run0"]["round_times"]) <= 8


def test_fused_schedule_matches_per_round(dataset_dir, tmp_path):
    """--fused-schedule (whole-schedule lax.scan in chunks, VERDICT r1 #7)
    must produce the same rounds, metrics, and artifacts as the per-round
    path — including when early stopping fires mid-chunk (rewind+replay)."""
    root, cfg_path = dataset_dir
    common = [
        "--dataset-config", cfg_path,
        "--model-types", "hybrid", "--update-types", "avg",
        "--network-size", "4", "--dim-features", str(DIM),
        "--epochs", "2", "--num-rounds", "5", "--batch-size", "8",
        "--no-save",
    ]
    # fused_schedule now defaults True, so path A must opt OUT explicitly
    # to keep this a per-round-vs-schedule equivalence test
    out_a = cli_main(common + ["--checkpoint-dir", str(tmp_path / "a"),
                               "--experiment-name", "sched_a",
                               "--fused-schedule", "false"])
    out_b = cli_main(common + ["--checkpoint-dir", str(tmp_path / "b"),
                               "--experiment-name", "sched_b",
                               "--fused-schedule", "true",
                               "--fused-schedule-chunk", "2"])
    ra = out_a["results"]["hybrid/avg/run0"]
    rb = out_b["results"]["hybrid/avg/run0"]
    assert len(ra["round_times"]) == len(rb["round_times"])  # same stop round
    # rtol matches the documented scan-vs-per-round equivalence (config.py:
    # XLA may reorder float ops between the two compilations)
    np.testing.assert_allclose(ra["final_metrics"], rb["final_metrics"],
                               rtol=1e-4)

    def rows(d, exp):
        path = glob.glob(os.path.join(d, "Results", "Update", "4", exp,
                                      "Run_0", "AUC", "*.json"))[0]
        return [json.loads(l) for l in open(path)]

    rows_a = rows(str(tmp_path / "a"), "sched_a")
    rows_b = rows(str(tmp_path / "b"), "sched_b")
    assert [r["round"] for r in rows_a] == [r["round"] for r in rows_b]
    for qa, qb in zip(rows_a, rows_b):
        np.testing.assert_allclose(qa["client_metrics"], qb["client_metrics"],
                                   rtol=1e-4)


def test_compat_flags_reach_cli():
    """Every CompatConfig quirk switch is CLI-flippable (VERDICT r1 #9)."""
    import dataclasses as dc

    from fedmse_tpu.config import (CompatConfig, add_cli_overrides,
                                   apply_cli_overrides)
    import argparse

    for f in dc.fields(CompatConfig):
        p = argparse.ArgumentParser()
        add_cli_overrides(p)
        flag = "--compat-" + f.name.replace("_", "-")
        args = p.parse_args([flag, "false"])
        cfg = apply_cli_overrides(ExperimentConfig(), args)
        assert getattr(cfg.compat, f.name) is False, f.name
        # untouched flags keep their quirk-mode defaults
        others = [g.name for g in dc.fields(CompatConfig) if g.name != f.name]
        assert all(getattr(cfg.compat, o) == getattr(CompatConfig(), o)
                   for o in others)


def test_compat_quirk6_changes_verification_data(dataset_dir):
    """Fixed mode vs quirk mode diverge where expected: quirk 6 off gives
    each client its OWN valid split as verification data instead of the
    last client's (src/main.py:264)."""
    import jax.numpy as jnp

    from fedmse_tpu.config import CompatConfig
    from fedmse_tpu.data import (build_dev_dataset, prepare_clients,
                                 stack_clients)
    from fedmse_tpu.federation import RoundEngine
    from fedmse_tpu.models import make_model
    from fedmse_tpu.utils.seeding import ExperimentRngs

    root, cfg_path = dataset_dir
    ds = DatasetConfig.from_json(cfg_path)
    cfg = ExperimentConfig(dim_features=DIM, network_size=4, epochs=1,
                           num_rounds=1, batch_size=8)
    rngs = ExperimentRngs(run=0)
    clients = prepare_clients(ds, cfg, rngs.data_rng)
    data = stack_clients(clients, build_dev_dataset(clients, rngs.data_rng),
                         cfg.batch_size)
    model = make_model("hybrid", DIM)

    def ver_x(compat):
        e = RoundEngine(model, cfg.replace(compat=compat), data, n_real=4,
                        rngs=ExperimentRngs(run=0), model_type="hybrid",
                        update_type="avg")
        return e._ver_x

    quirk = ver_x(CompatConfig())
    fixed = ver_x(CompatConfig(shared_last_client_val=False))
    # quirk mode: every client sees the LAST client's valid split
    assert jnp.allclose(quirk[0], quirk[3])
    # fixed mode: clients see their own (different) splits
    assert not jnp.allclose(fixed[0], fixed[3])


def test_checkpoint_tracking_roundtrip(tmp_path):
    """Resume keeps the pre-kill training curve so training_tracking.pkl
    covers ALL rounds, not just post-resume ones (VERDICT r1 #8)."""
    import jax
    import optax

    from fedmse_tpu.checkpointing import CheckpointManager
    from fedmse_tpu.federation.state import HostState, init_client_states
    from fedmse_tpu.models import make_model

    model = make_model("hybrid", DIM)
    states = init_client_states(model, optax.adam(1e-3), jax.random.key(0), 3)
    host = HostState.create(3)
    curve = np.arange(3 * 4 * 3, dtype=np.float32).reshape(3, 4, 3)

    mgr = CheckpointManager(str(tmp_path / "ck"))
    mgr.save("t", states, host, 2, tracking=curve)
    _, _, rnd, restored = mgr.restore("t", states)
    assert rnd == 2
    np.testing.assert_array_equal(restored, curve)

    # tracking is optional: a save without it restores None
    mgr.save("u", states, host, 1)
    assert mgr.restore("u", states)[3] is None


def test_restore_validates_layout_changing_config(tmp_path):
    """A checkpoint written under one opt_state layout must refuse a
    restore under another WITH A CLEAR MESSAGE naming the flag —
    flatten_optimizer flips the Adam state pytree, and without the guard
    the mismatch surfaces as a cryptic Orbax tree-structure error."""
    import jax
    import optax
    import pytest

    from fedmse_tpu.checkpointing import CheckpointManager
    from fedmse_tpu.federation.state import HostState, init_client_states
    from fedmse_tpu.models import make_model

    model = make_model("hybrid", DIM)
    states = init_client_states(model, optax.adam(1e-3), jax.random.key(0), 3)
    host = HostState.create(3)
    mgr = CheckpointManager(str(tmp_path / "ck"))
    mgr.save("t", states, host, 1, extra={"flatten_optimizer": False})

    with pytest.raises(ValueError, match="flatten_optimizer"):
        mgr.restore("t", states, expected_extra={"flatten_optimizer": True})
    # matching flag restores fine; keys absent from the checkpoint (older
    # snapshots) are not validated
    assert mgr.restore("t", states,
                       expected_extra={"flatten_optimizer": False,
                                       "not_recorded": 1})[2] == 1
