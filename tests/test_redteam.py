"""Redteam adversaries + measured defenses (fedmse_tpu/redteam/,
DESIGN.md §21), with the acceptance contracts pinned:

  * a NULL RedteamSpec produces a program bit-identical to no spec at
    all (states pinned across dense; the tiered layout accepts only a
    null spec and rejects active ones eagerly);
  * the coalition draw is absolute-id keyed: padding the client axis
    never moves which slots are adversarial (PARITY §8);
  * the election compiles the tenure gate BEFORE the collusion pick, so
    a gated sybil cannot be elected even by an accomplice;
  * off-schedule rounds apply no poison (the lax.cond identity branch
    is bitwise);
  * the hardened verifier's recovery waiver consumes a CUMULATIVE
    budget (config.recovery_budget): the PR 1 gameability cap;
  * the flywheel admission defenses (margin floor, influence cap)
    exclude exactly the adversarial band and default to byte-identical
    off;
  * assignment hysteresis holds borderline moves, and the 'gmm' metric
    matches its numpy f64 oracle (utils/similarity.py) at f32 tolerance.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fedmse_tpu.cluster import (ClusterSpec, fit_gateway_gmms,
                                js_to_references, moment_match_gmms,
                                pairwise_gmm_js, refit_with_hysteresis)
from fedmse_tpu.config import CompatConfig, ExperimentConfig
from fedmse_tpu.data import build_dev_dataset, stack_clients, synthetic_clients
from fedmse_tpu.federation import RoundEngine
from fedmse_tpu.federation.elastic import ElasticSpec, MembershipMasks
from fedmse_tpu.flywheel.buffer import FlywheelBuffer
from fedmse_tpu.models import make_model
from fedmse_tpu.redteam import (RedteamSpec, SlowDriftAdversary,
                                assignment_capture_rate, coalition_mask,
                                make_redteam_fns, make_redteam_masks,
                                mimic_latent_stats, normal_fraction,
                                tenure_vote_ok)
from fedmse_tpu.utils.seeding import ExperimentRngs
from fedmse_tpu.utils.similarity import gmm_js as gmm_js_oracle

pytestmark = pytest.mark.redteam

DIM = 12
N = 4


def build_cfg(**kw):
    return ExperimentConfig(
        dim_features=DIM, network_size=N, epochs=2, batch_size=8,
        compat=CompatConfig(vote_tie_break=False), **kw)


@pytest.fixture(scope="module")
def data():
    cfg = build_cfg()
    clients = synthetic_clients(n_clients=N, dim=DIM, n_normal=120,
                                n_abnormal=60)
    dev_x = build_dev_dataset(clients, ExperimentRngs(run=0).data_rng)
    return stack_clients(clients, dev_x, cfg.batch_size)


def build_engine(cfg, data, redteam=None, elastic=None, run=0):
    m = make_model("hybrid", DIM, shrink_lambda=cfg.shrink_lambda)
    return RoundEngine(m, cfg, data, n_real=N, rngs=ExperimentRngs(run=run),
                       model_type="hybrid", update_type="avg", fused=True,
                       redteam=redteam, elastic=elastic)


# ---------------------------------------------------------------- spec ----

def test_spec_validation_rejects_bad_values():
    with pytest.raises(ValueError, match="kind"):
        RedteamSpec(kind="zero")
    with pytest.raises(ValueError, match="poison"):
        RedteamSpec(poison="typo")
    with pytest.raises(ValueError, match="adversary_frac"):
        RedteamSpec(adversary_frac=1.5)
    with pytest.raises(ValueError, match="non-empty"):
        RedteamSpec(kind="sybil", adversaries=())
    with pytest.raises(ValueError, match="duplicate"):
        RedteamSpec(kind="sybil", adversaries=(1, 1))
    with pytest.raises(ValueError, match="coalition"):
        RedteamSpec(kind="cluster_poison")  # attack with no attackers
    with pytest.raises(ValueError, match="every_k"):
        RedteamSpec(kind="sybil", adversaries=(0,), every_k=0)
    with pytest.raises(ValueError, match="stop_round"):
        RedteamSpec(kind="sybil", adversaries=(0,), start_round=3,
                    stop_round=3)
    with pytest.raises(ValueError, match="min_tenure"):
        RedteamSpec(min_tenure=-1)
    assert RedteamSpec().is_null
    assert not RedteamSpec(min_tenure=2).is_null       # defense-only
    assert not RedteamSpec(kind="sybil", adversaries=(0,)).is_null


def test_null_and_defense_only_fns():
    assert make_redteam_fns(None) is None
    assert make_redteam_fns(RedteamSpec()) is None
    fns = make_redteam_fns(RedteamSpec(min_tenure=2))
    assert fns.update_fn is None and fns.merge_fn is None
    assert fns.gate_votes and not fns.lie_votes
    fns = make_redteam_fns(RedteamSpec(kind="sybil", adversaries=(1,),
                                       lie_votes=True, min_tenure=1))
    assert fns.update_fn is not None and fns.lie_votes and fns.gate_votes


# --------------------------------------------------------------- masks ----

def test_coalition_padding_invariance():
    """The frac-drawn coalition is keyed by ABSOLUTE slot id: the n=8
    build is the exact prefix of the n=12 build (PARITY §8)."""
    spec = RedteamSpec(kind="sybil", adversary_frac=0.5)
    key = ExperimentRngs(run=0).redteam_key()
    a = np.asarray(coalition_mask(spec, key, 8))
    b = np.asarray(coalition_mask(spec, key, 12))
    np.testing.assert_array_equal(a, b[:8])
    # ... and the draw reproduces from the key
    np.testing.assert_array_equal(a, np.asarray(coalition_mask(spec, key, 8)))


def test_explicit_ids_and_out_of_range_drop():
    spec = RedteamSpec(kind="sybil", adversaries=(1, 9))
    key = ExperimentRngs(run=0).redteam_key()
    adv = np.asarray(coalition_mask(spec, key, 4))
    np.testing.assert_array_equal(adv, [0.0, 1.0, 0.0, 0.0])
    m = make_redteam_masks(spec, key, 3, 4)
    assert np.asarray(m.adv).shape == (3, 4)
    np.testing.assert_array_equal(np.asarray(m.vote_ok), 1.0)


def test_tenure_gate_spares_founders_and_gates_recycled():
    # timeline: slot 2 is recycled at round 1 (generation 1); slots 0-1
    # are founding tenants, slot 3 leaves at round 2
    member = np.array([[1, 1, 0, 1], [1, 1, 1, 1], [1, 1, 1, 0],
                       [1, 1, 1, 0]], np.float32)
    joined = np.zeros((4, 4), np.float32)
    joined[1, 2] = 1.0
    left = np.zeros((4, 4), np.float32)
    left[2, 3] = 1.0
    gen = np.zeros((4, 4), np.int32)
    gen[1:, 2] = 1
    mm = MembershipMasks(member=jnp.asarray(member),
                         joined=jnp.asarray(joined),
                         left=jnp.asarray(left),
                         generation=jnp.asarray(gen))
    ok = tenure_vote_ok(2, mm, 4, 4)
    # founders are never gated
    np.testing.assert_array_equal(ok[:, 0], 1.0)
    np.testing.assert_array_equal(ok[:, 1], 1.0)
    # the recycled tenant is gated on its join round (streak 1 < 2) and
    # eligible from the next (streak 2)
    np.testing.assert_array_equal(ok[:, 2], [1.0, 0.0, 1.0, 1.0])


def test_min_tenure_requires_membership():
    spec = RedteamSpec(min_tenure=2)
    key = ExperimentRngs(run=0).redteam_key()
    with pytest.raises(ValueError, match="membership"):
        make_redteam_masks(spec, key, 4, 4)


# ------------------------------------------------------------ adversary ----

def test_off_schedule_rounds_apply_no_poison():
    spec = RedteamSpec(kind="cluster_poison", adversaries=(1,),
                       poison="scale", strength=100.0, start_round=2,
                       every_k=2, stop_round=7)
    fns = make_redteam_fns(spec)
    params = {"w": jnp.ones((4, 3)), "b": jnp.arange(4, dtype=jnp.float32)}
    adv = jnp.asarray([0.0, 1.0, 0.0, 0.0])
    rng = jax.random.key(0)
    for r, active in [(0, False), (1, False), (2, True), (3, False),
                      (4, True), (7, False), (8, False)]:
        out = fns.update_fn(params, adv, jnp.asarray(r), rng)
        changed = bool(np.any(np.asarray(out["w"]) != np.asarray(params["w"])))
        assert changed == active, f"round {r}"
        if active:
            # only the adversarial row moves
            np.testing.assert_array_equal(np.asarray(out["w"])[0],
                                          np.asarray(params["w"])[0])
            np.testing.assert_array_equal(np.asarray(out["w"])[1], 100.0)


def test_merge_poison_scopes_to_victim_cluster_row():
    spec = RedteamSpec(kind="cluster_poison", adversaries=(1,),
                       victim_cluster=1, poison="sign_flip", strength=2.0)
    fns = make_redteam_fns(spec)
    cluster_params = {"w": jnp.ones((3, 5))}  # [K=3, ...]
    out = fns.merge_fn(cluster_params, jnp.asarray(True), jnp.asarray(0),
                       jax.random.key(0), clustered=True)
    w = np.asarray(out["w"])
    np.testing.assert_array_equal(w[0], 1.0)   # other clusters untouched
    np.testing.assert_array_equal(w[1], -2.0)  # victim row flipped
    np.testing.assert_array_equal(w[2], 1.0)
    # an honest aggregator never fires the merge stage
    out = fns.merge_fn(cluster_params, jnp.asarray(False), jnp.asarray(0),
                       jax.random.key(0), clustered=True)
    np.testing.assert_array_equal(np.asarray(out["w"]), 1.0)


# ------------------------------------------------------------- election ----

def _elect(scores, sel, adv=None, vote_ok=None, lie=False):
    from fedmse_tpu.federation.fused import _elect_on_device
    n = len(scores)
    scores = np.asarray(scores, np.float32)

    def scores_fn(params, vote_x, vote_m, rng):
        return jnp.asarray(scores)

    agg, _ = _elect_on_device(
        scores_fn, None, jnp.asarray(sel, jnp.int32),
        jnp.ones((n,), jnp.float32), jnp.zeros((n,), jnp.int32),
        jnp.zeros((2, 2)), jnp.ones((2, 2)), jax.random.key(0), 100,
        vote_ok=None if vote_ok is None else jnp.asarray(vote_ok,
                                                         jnp.float32),
        adv=None if adv is None else jnp.asarray(adv, jnp.float32),
        lie_votes=lie)
    return int(agg)


def test_lying_voter_elects_accomplice():
    # honest rank: slot 2 has the best (lowest) score among candidates
    scores = [0.9, 0.5, 0.1, 0.7]
    sel = [0, 1, 2, 3]
    assert _elect(scores, sel) == 2
    # voter 0 is adversarial with accomplice 3: collusion overrides rank
    adv = [1.0, 0.0, 0.0, 1.0]
    assert _elect(scores, sel, adv=adv, lie=True) == 3
    # an honest voter with adversaries in the fleet still ranks honestly
    adv = [0.0, 0.0, 0.0, 1.0]
    assert _elect(scores, sel, adv=adv, lie=True) == 2


def test_tenure_gate_blocks_colluding_election():
    """The vote_ok gate lands BEFORE the collusion pick: a tenure-gated
    sybil cannot be elected even by an adversarial accomplice."""
    scores = [0.9, 0.5, 0.1, 0.7]
    sel = [0, 1, 2, 3]
    adv = [1.0, 0.0, 0.0, 1.0]
    vote_ok = [1.0, 1.0, 1.0, 0.0]  # the accomplice is gated
    assert _elect(scores, sel, adv=adv, vote_ok=vote_ok, lie=True) == 2
    # a gated voter casts no vote: its turn passes to the next voter
    vote_ok = [0.0, 1.0, 1.0, 1.0]
    assert _elect(scores, sel, adv=adv, vote_ok=vote_ok, lie=True) == 2


# -------------------------------------------------- engine bit-identity ----

def test_null_spec_is_bitwise_off(data):
    """A null RedteamSpec (and spec=None) compiles the identical program:
    states after 3 dense fused rounds are bitwise equal."""
    cfg = build_cfg()
    engines = [build_engine(cfg, data),
               build_engine(cfg, data, redteam=RedteamSpec())]
    for e in engines:
        for r in range(3):
            e.run_round_fused(r)
    for a, b in zip(jax.tree.leaves(engines[0].states),
                    jax.tree.leaves(engines[1].states)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_attack_changes_states_and_chunk_parity(data):
    """An active coalition perturbs the federation, and per-round vs
    scanned-chunk dispatch agree bitwise with the hooks compiled in."""
    cfg = build_cfg(num_rounds=3)
    spec = RedteamSpec(kind="cluster_poison", adversaries=(1,),
                       poison="scale", strength=50.0)
    off = build_engine(cfg, data)
    ea = build_engine(cfg, data, redteam=spec)
    eb = build_engine(cfg, data, redteam=spec)
    for r in range(3):
        off.run_round_fused(r)
        ea.run_round_fused(r)
    eb.run_schedule_chunk(0, 3)
    assert any(
        not np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(off.states.params),
                        jax.tree.leaves(ea.states.params)))
    for a, b in zip(jax.tree.leaves(ea.states), jax.tree.leaves(eb.states)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_tiered_layout_accepts_only_null_spec(data):
    """The tiered layout (which host_sharded degenerates to in one
    process) takes a null spec bitwise-free and rejects an active one
    eagerly — redteam hooks live in the dense fused body only."""
    from fedmse_tpu.federation.tiered import TieredRoundEngine
    cfg = build_cfg(state_layout="tiered", num_rounds=2)
    m = make_model("hybrid", DIM, shrink_lambda=cfg.shrink_lambda)

    def tiered(**kw):
        return TieredRoundEngine(m, cfg, data, n_real=N,
                                 rngs=ExperimentRngs(run=0),
                                 model_type="hybrid", update_type="avg",
                                 **kw)

    # null spec: accepted AND bitwise-identical to no spec at all
    plain, null = tiered(), tiered(redteam=RedteamSpec())
    for e in (plain, null):
        e.run_rounds(0, 2, lambda r, s: False)
    for a, b in zip(jax.tree.leaves(plain.states_for_checkpoint(N)),
                    jax.tree.leaves(null.states_for_checkpoint(N))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    with pytest.raises(ValueError, match="dense"):
        tiered(redteam=RedteamSpec(kind="sybil", adversaries=(1,)))


def test_redteam_requires_fused_engine(data):
    cfg = build_cfg()
    m = make_model("hybrid", DIM, shrink_lambda=cfg.shrink_lambda)
    with pytest.raises(ValueError, match="fused"):
        RoundEngine(m, cfg, data, n_real=N, rngs=ExperimentRngs(run=0),
                    model_type="hybrid", update_type="avg", fused=False,
                    redteam=RedteamSpec(kind="sybil", adversaries=(1,)))
    with pytest.raises(ValueError, match="ElasticSpec"):
        build_engine(cfg, data, redteam=RedteamSpec(min_tenure=2))


# -------------------------------------------- verification budget (PR 1) ----

def test_recovery_budget_caps_cumulative_waivers():
    """The hardened verifier's recovery waiver consumes a CUMULATIVE
    per-client budget: once states.waived crosses it, further broadcasts
    must pass the ordinary delta cap — the enforced version of the
    make_verify_fn CAVEAT's shared-tensor gameability."""
    from fedmse_tpu.federation.state import init_client_states
    from fedmse_tpu.federation.verification import make_verify_fn
    import optax

    model = make_model("hybrid", DIM)
    tx = optax.adam(1e-3)
    states = init_client_states(model, tx, jax.random.key(0), N)
    # every client has verifier history (first-contact waivers are not
    # the surface under test — they never consume budget)
    states = type(states)(
        params=states.params, opt_state=states.opt_state,
        prev_global=states.prev_global, hist_params=states.hist_params,
        hist_perf=states.hist_perf,
        hist_seen=jnp.ones((N,), bool), rejected=states.rejected,
        waived=states.waived)
    # recovery_threshold=-1 makes every broadcast "recover" (the waiver
    # qualifies unconditionally) so the test isolates the BUDGET gate;
    # verification_threshold ~0 forces the waiver to be load-bearing
    common = dict(verification_threshold=1e-6, performance_threshold=10.0,
                  hardened=True, recovery_threshold=-1.0,
                  recovery_delta_cap=1e9)
    ver_x = jnp.zeros((N, 6, DIM))
    ver_m = jnp.ones((N, 6))
    agg_onehot = jnp.zeros((N,))
    client_mask = jnp.ones((N,))
    # accepted broadcasts overwrite client params with the aggregator's,
    # so each probe must move FURTHER to re-trip the waiver
    agg1 = jax.tree.map(lambda t: t[0] + 0.5, states.params)
    agg2 = jax.tree.map(lambda t: t[0] + 1.5, states.params)

    # no budget: waived accumulates but every attempt is accepted
    verify = make_verify_fn(model, **common)
    out1 = verify(states, agg1, ver_x, ver_m, agg_onehot, client_mask)
    assert bool(np.all(np.asarray(out1.accepted)))
    waived1 = np.asarray(out1.states.waived)
    assert (waived1 > 0).all()
    np.testing.assert_allclose(waived1, np.asarray(out1.param_delta),
                               rtol=1e-6)
    out2 = verify(out1.states, agg2, ver_x, ver_m, agg_onehot, client_mask)
    assert bool(np.all(np.asarray(out2.accepted)))
    assert (np.asarray(out2.states.waived) > waived1).all()

    # budget below one waived step: the first waiver lands, the second is
    # over budget and rejected
    verify_b = make_verify_fn(model, recovery_budget=float(waived1.min()),
                              **common)
    out1b = verify_b(states, agg1, ver_x, ver_m, agg_onehot, client_mask)
    assert bool(np.all(np.asarray(out1b.accepted)))
    out2b = verify_b(out1b.states, agg2, ver_x, ver_m, agg_onehot,
                     client_mask)
    assert not bool(np.any(np.asarray(out2b.accepted)))
    # a rejected attempt charges nothing
    np.testing.assert_allclose(np.asarray(out2b.states.waived),
                               np.asarray(out1b.states.waived))


# ------------------------------------------------- flywheel admission ----

def test_margin_floor_excludes_near_threshold_rows():
    thr = np.array([1.0, 1.0])
    buf = FlywheelBuffer(2, DIM, capacity=16, margin_frac=0.5,
                         thresholds_fn=lambda: thr)
    rows = np.ones((4, DIM), np.float32)
    gw = np.array([0, 0, 1, 1])
    verdicts = np.zeros(4, bool)  # all verdicted normal
    scores = np.array([0.2, 0.9, 0.4, 0.51])  # floor at 0.5 x 1.0
    admitted = buf.admit(rows, gw, verdicts=verdicts, scores=scores)
    assert admitted == 2
    assert buf.count.tolist() == [1, 1]
    # margin off: byte-identical admission of everything verdicted normal
    buf2 = FlywheelBuffer(2, DIM, capacity=16)
    assert buf2.admit(rows, gw, verdicts=verdicts, scores=scores) == 4


def test_margin_floor_validation():
    with pytest.raises(ValueError, match="thresholds_fn"):
        FlywheelBuffer(2, DIM, margin_frac=0.5)
    with pytest.raises(ValueError, match="margin_frac"):
        FlywheelBuffer(2, DIM, margin_frac=1.5,
                       thresholds_fn=lambda: np.ones(2))
    with pytest.raises(ValueError, match="influence_cap"):
        FlywheelBuffer(2, DIM, influence_cap=0.0)


def test_influence_cap_bounds_one_gateways_share():
    rng = np.random.default_rng(0)
    buf = FlywheelBuffer(3, DIM, capacity=128, influence_cap=0.34)
    buf.admit(rng.normal(size=(100, DIM)), np.full(100, 0))  # flooder
    buf.admit(rng.normal(size=(20, DIM)), np.full(20, 1))
    buf.admit(rng.normal(size=(20, DIM)), np.full(20, 2))
    ft = buf.build_finetune_data(8, dev_x=np.zeros((4, DIM), np.float32),
                                 min_rows=8)
    lens = [len(r) for r in ft.train_rows]
    cap = max(1, int(0.34 * sum(
        len(buf.rows_for(g)) - max(1, int(round(0.25 * len(
            buf.rows_for(g))))) for g in range(3))))
    assert lens[0] <= cap
    # uncapped: the flooder dominates
    buf2 = FlywheelBuffer(3, DIM, capacity=128)
    buf2.admit(rng.normal(size=(100, DIM)), np.full(100, 0))
    buf2.admit(rng.normal(size=(20, DIM)), np.full(20, 1))
    buf2.admit(rng.normal(size=(20, DIM)), np.full(20, 2))
    ft2 = buf2.build_finetune_data(8, dev_x=np.zeros((4, DIM), np.float32),
                                   min_rows=8)
    lens2 = [len(r) for r in ft2.train_rows]
    assert lens2[0] > lens[0]
    assert lens2[0] > lens2[1] + lens2[2]


# ------------------------------------------------ slow-drift adversary ----

def test_slow_drift_adapts_to_verdict_feedback():
    adv = SlowDriftAdversary(np.zeros(DIM), np.full(DIM, 5.0), step=0.1)
    assert adv.position == 0.0
    adv.observe(1.0)
    assert adv.position == pytest.approx(0.1)
    adv.observe(0.95)
    assert adv.position == pytest.approx(0.2)
    adv.observe(0.2)  # detector pushes back: retreat a half-step
    assert adv.position == pytest.approx(0.15)
    batch = adv.next_batch(32)
    assert batch.shape == (32, DIM)
    np.testing.assert_allclose(batch.mean(axis=0), adv.mu(), atol=0.1)
    probe = adv.target_rows(16, seed=7)
    np.testing.assert_array_equal(probe, SlowDriftAdversary(
        np.zeros(DIM), np.full(DIM, 5.0)).target_rows(16, seed=7))
    assert normal_fraction(np.array([False, False, True, False])) == 0.75
    assert normal_fraction(np.zeros(0, bool)) == 0.0


# ------------------------------------------------------------- mimicry ----

def test_perfect_mimicry_captures_victim_cluster():
    """blend=1.0 forges the victim's exact latent Gaussian: the JS
    assignment cannot distinguish forged from genuine — the provable
    failure point the DESIGN.md §21 threat table records."""
    rng = np.random.default_rng(0)
    means = np.stack([np.zeros(5), np.zeros(5) + 0.1,
                      np.full(5, 8.0), np.full(5, 8.1)]).astype(np.float32)
    covs = np.tile(np.eye(5, dtype=np.float32), (4, 1, 1))
    covs += 0.01 * rng.normal(size=covs.shape).astype(np.float32)
    covs = 0.5 * (covs + covs.transpose(0, 2, 1))
    covs += 0.5 * np.eye(5, dtype=np.float32)
    victim_mu, victim_cov = means[0], covs[0]
    # adversaries 2, 3 start statistically far from the victim
    m1, c1 = mimic_latent_stats(means, covs, (2, 3), victim_mu, victim_cov,
                                blend=1.0)
    np.testing.assert_allclose(m1[2], victim_mu, atol=1e-6)
    np.testing.assert_allclose(c1[2], victim_cov, atol=1e-5)
    # honest gateways' stats are untouched
    np.testing.assert_array_equal(m1[0], means[0])
    np.testing.assert_array_equal(c1[1], covs[1])
    # a JS nearest-reference assignment now groups them with the victim
    refs_m = np.stack([means[0], means[2]])
    refs_c = np.stack([covs[0], covs[2]])
    js = np.asarray(js_to_references(jnp.asarray(m1), jnp.asarray(c1),
                                     jnp.asarray(refs_m),
                                     jnp.asarray(refs_c)))
    assign = np.argmin(js, axis=1)
    assert assignment_capture_rate(assign, (2, 3), 0) == 1.0
    # blend=0 is the identity
    m0, c0 = mimic_latent_stats(means, covs, (2, 3), victim_mu, victim_cov,
                                blend=0.0)
    np.testing.assert_allclose(m0, means, atol=1e-7)
    np.testing.assert_allclose(c0, covs, atol=1e-7)


# --------------------------------------------- hysteresis + GMM metric ----

def test_cluster_spec_new_knobs_validate():
    with pytest.raises(ValueError, match="hysteresis"):
        ClusterSpec(k=2, hysteresis=1.0)
    with pytest.raises(ValueError, match="gmm_components"):
        ClusterSpec(k=2, metric="gmm", gmm_components=0)
    with pytest.raises(ValueError, match="metric"):
        ClusterSpec(k=2, metric="kde")
    s = ClusterSpec(k=2, hysteresis=0.3, metric="gmm", gmm_components=3)
    assert "h0.3" in s.signature() and "c3" in s.signature()
    assert ClusterSpec(k=2).signature() == ClusterSpec(k=2).signature()
    # defaults keep the pre-PR signature (checkpoint compat)
    assert "h" not in ClusterSpec(k=2).signature().split("mjs")[-1]


def test_hysteresis_holds_borderline_and_allows_decisive_moves():
    rng = np.random.default_rng(1)
    means = np.stack([np.zeros(4), np.zeros(4) + 0.2,
                      np.full(4, 6.0), np.full(4, 6.2)]).astype(np.float32)
    covs = np.tile(np.eye(4, dtype=np.float32), (4, 1, 1))
    prev = np.array([0, 0, 1, 1], np.int32)
    held = refit_with_hysteresis(means, covs, prev, 2, 0.5)
    np.testing.assert_array_equal(held.assignment, prev)
    # a decisive shift (gateway 1 lands on cluster 1's center) moves
    moved = means.copy()
    moved[1] = means[2]
    out = refit_with_hysteresis(moved, covs, prev, 2, 0.5)
    assert out.assignment[1] == out.assignment[2]
    # labels never permute: gateway 0 keeps its cluster id
    assert out.assignment[0] == prev[0]
    # h=0 reduces to plain nearest-reference moves
    out0 = refit_with_hysteresis(moved, covs, prev, 2, 0.0)
    assert out0.assignment[1] == out0.assignment[2]


def test_gmm_js_matches_numpy_oracle():
    rng = np.random.default_rng(0)

    def rows(mu_list, n=60):
        return np.concatenate(
            [rng.normal(m, 0.3, (n, 5)) for m in mu_list])

    lat = np.stack([rows([0.0, 4.0]), rows([0.1, 4.1]),
                    rows([8.0, 8.0]), rows([8.1, 8.1])]).astype(np.float32)
    w, mu, cv = fit_gateway_gmms(lat, None, components=2, iters=8)
    # EM is a pure function of the rows (no RNG stream)
    w2, mu2, cv2 = fit_gateway_gmms(lat, None, components=2, iters=8)
    np.testing.assert_array_equal(w, w2)
    np.testing.assert_array_equal(mu, mu2)
    np.testing.assert_array_equal(cv, cv2)
    # the bimodal gateways split ~50/50; f32 jax vs f64 numpy oracle
    assert abs(w[0, 0] - 0.5) < 0.1
    jm = np.asarray(pairwise_gmm_js(jnp.asarray(w, jnp.float32),
                                    jnp.asarray(mu, jnp.float32),
                                    jnp.asarray(cv, jnp.float32)))
    om = np.array([[gmm_js_oracle(w[a], mu[a], cv[a], w[b], mu[b], cv[b])
                    for b in range(4)] for a in range(4)])
    np.testing.assert_allclose(jm, om, rtol=1e-4, atol=1e-4)
    # moment matching preserves the mixture mean exactly
    mm_mean, mm_cov = moment_match_gmms(w, mu, cv)
    np.testing.assert_allclose(
        mm_mean[0], np.einsum("m,ml->l", w[0], mu[0]), atol=1e-6)
    assert mm_cov.shape == (4, 5, 5)


def test_gmm_metric_separates_multimodal_gateways():
    """Two bimodal gateways sharing modes vs two unimodal ones: the gmm
    metric groups by mixture structure."""
    from fedmse_tpu.cluster import fit_assignments_gmm
    rng = np.random.default_rng(0)

    def rows(mu_list, n=50):
        return np.concatenate(
            [rng.normal(m, 0.3, (n, 4)) for m in mu_list])

    lat = np.stack([rows([0.0, 4.0]), rows([0.1, 4.1]),
                    rows([2.0, 2.0]), rows([2.1, 2.1])]).astype(np.float32)
    asn = fit_assignments_gmm(None, lat, None, 2)
    assert asn.assignment[0] == asn.assignment[1]
    assert asn.assignment[2] == asn.assignment[3]
    assert asn.assignment[0] != asn.assignment[2]
    assert asn.means.shape == (4, 4)  # moment-matched storage shapes
    assert asn.covs.shape == (4, 4, 4)
