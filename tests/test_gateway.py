"""Gateway ingest-plane tests (fedmse_tpu/gateway/, DESIGN.md §22):
mux wire roundtrips, per-device key derivation + transcript MACs, the
handshake-time roster gate (every reject path pinned at ZERO parsed row
bytes), session reuse + parking across bursts, frontend-striped scoring
bit-identical to a direct net-plane router over the same seeded fleet,
failover with zero admitted-ticket loss, the per-session isolation cap
through the router's session_key path, FrameBuffer offset consumption,
and the two-class (frontend/replica) autoscale sizing with scale-down
confirmation hysteresis."""

import time

import numpy as np
import pytest

from fedmse_tpu.gateway import auth, mux
from fedmse_tpu.gateway.client import GatewayClient
from fedmse_tpu.gateway.frontend import (FrontendHandle,
                                         build_synthetic_frontend)
from fedmse_tpu.gateway.stripe import FailoverStripe
from fedmse_tpu.net import wire
from fedmse_tpu.net.admission import SessionIsolation
from fedmse_tpu.net.autoscale import (BackendSpec, FrontendSpec,
                                      SLOAutoscaler, plan_split)
from fedmse_tpu.net.router import Router
from fedmse_tpu.net.server import build_synthetic_replicas
from fedmse_tpu.redteam.ingest import InstantReplica
from fedmse_tpu.serving.engine import ServingRoster

pytestmark = pytest.mark.gateway

DIM = 12
N = 16


def _wait(pred, timeout_s=20.0, tick=0.005):
    deadline = time.time() + timeout_s
    while not pred():
        if time.time() > deadline:
            raise TimeoutError("condition not met in time")
        time.sleep(tick)


def _wait_reject(client, code, timeout_s=20.0):
    _wait(lambda: (client.poll(),
                   any(c == code for _, c, _ in client.rejects))[1],
          timeout_s=timeout_s)


def _small_front(**kw):
    kw.setdefault("n_gateways", N)
    kw.setdefault("dim", DIM)
    kw.setdefault("replicas", 1)
    kw.setdefault("max_batch", 32)
    kw.setdefault("model_type", "autoencoder")
    kw.setdefault("seed", 0)
    return build_synthetic_frontend(**kw)


# ------------------------------- wire ---------------------------------- #


def test_mux_roundtrips():
    fb = wire.FrameBuffer()
    cn, sn = auth.new_nonce(), auth.new_nonce()
    token = auth.new_nonce()
    rows = np.arange(3 * DIM, dtype=np.float32).reshape(3, DIM)
    statuses = np.array([0, 1, 2], np.uint8)
    scores = np.array([0.5, 2.0, np.nan], np.float32)
    fb.feed(mux.pack_hello(7, 3, cn))
    fb.feed(mux.pack_challenge(7, sn))
    fb.feed(mux.pack_auth(7, b"m" * mux.MAC_LEN))
    fb.feed(mux.pack_welcome(7, token))
    fb.feed(mux.pack_reject(9, mux.REJ_BAD_MAC, "nope"))
    fb.feed(mux.pack_submit(7, 11, token, rows, tier=2))
    fb.feed(mux.pack_result(7, 11, statuses, scores))
    fb.feed(mux.pack_simple(mux.G_PING, 7, 5))
    frames = list(fb.frames())
    assert [mux.parse_gheader(p)[0] for p in frames] == [
        mux.G_HELLO, mux.G_CHALLENGE, mux.G_AUTH, mux.G_WELCOME,
        mux.G_REJECT, mux.G_SUBMIT, mux.G_RESULT, mux.G_PING]
    assert mux.unpack_hello(frames[0]) == (7, 3, cn)
    assert mux.unpack_challenge(frames[1]) == (7, sn)
    assert mux.unpack_auth(frames[2]) == (7, b"m" * mux.MAC_LEN)
    assert mux.unpack_welcome(frames[3]) == (7, token)
    assert mux.unpack_reject(frames[4]) == (9, mux.REJ_BAD_MAC, "nope")
    # the token reads BEFORE the row block — the pre-parse check order
    assert mux.submit_token(frames[5]) == token
    seq, r2, tier, t_sent = mux.unpack_submit_rows(frames[5])
    assert seq == 11 and tier == 2 and t_sent > 0
    np.testing.assert_array_equal(np.asarray(r2), rows)
    rgid, rseq, st2, sc2 = mux.unpack_result(frames[6])
    assert (rgid, rseq) == (7, 11)
    np.testing.assert_array_equal(st2, statuses)
    np.testing.assert_array_equal(sc2, scores)


def test_framebuffer_offset_consumption():
    """Frames arrive in arbitrary chunk boundaries; the buffer yields
    whole payloads, keeps partial tails, and compacts via offset (no
    per-frame memmove)."""
    fb = wire.FrameBuffer()
    frames = [mux.pack_simple(mux.G_PING, i) for i in range(50)]
    blob = b"".join(frames)
    got = []
    for i in range(0, len(blob), 7):      # deliberately frame-misaligned
        fb.feed(blob[i:i + 7])
        got.extend(mux.parse_gheader(p)[2] for p in fb.frames())
    assert got == list(range(50))
    assert len(fb) == 0
    assert fb._off == 0                    # fully-consumed buffer compacted


def test_auth_key_derivation_and_mac():
    master = auth.master_key(seed=3)
    k = auth.gateway_key(master, 5, 0)
    assert k != auth.gateway_key(master, 6, 0)       # per-device
    assert k != auth.gateway_key(master, 5, 1)       # per-generation
    cn, sn = auth.new_nonce(), auth.new_nonce()
    mac = auth.session_mac(k, 5, 0, cn, sn)
    assert auth.verify_session_mac(k, 5, 0, cn, sn, mac)
    assert not auth.verify_session_mac(k, 5, 0, sn, cn, mac)  # transcript
    wrong = auth.gateway_key(master, 5, 1)
    assert not auth.verify_session_mac(wrong, 5, 0, cn, sn, mac)


# ----------------------- handshake: the identity gate ------------------- #


def test_handshake_rejects_terminate_before_any_row_parse():
    """Every reject path — unknown id, retired slot, wrong generation,
    wrong key, forged token — terminates with the frontend having
    parsed ZERO row bytes (`rows_parsed` is incremented only after
    token verification, and the roster gate fires at G_HELLO)."""
    front = _small_front(warmup=False, calibrate=False)
    front.router.roster.member[3] = False            # a retired slot
    h = FrontendHandle(front)
    master = auth.master_key(seed=0)
    try:
        c = GatewayClient("127.0.0.1", h.port, master=master)
        assert not c.authenticate(N + 50)            # out of roster range
        assert not c.authenticate(3)                 # retired slot
        assert not c.authenticate(4, generation=9)   # generation mismatch
        assert [code for _, code, _ in c.rejects] == [
            mux.REJ_UNKNOWN_GATEWAY] * 3

        bad = GatewayClient("127.0.0.1", h.port,
                            key_fn=lambda g, gen: b"\x00" * 32)
        assert not bad.authenticate(5)               # wrong enrollment key
        assert bad.rejects[-1][1] == mux.REJ_BAD_MAC

        # a REAL session, then a forged bearer token on it: the token
        # check runs before unpack_submit_rows ever touches the rows
        assert c.authenticate(2)
        rows = np.zeros((4, DIM), np.float32)
        c._send(mux.pack_submit(2, 1, b"\x00" * mux.TOKEN_LEN, rows))
        _wait_reject(c, mux.REJ_BAD_TOKEN)
        assert front.rows_parsed == 0
        assert front.rejects["unknown_gateway"] == 3
        assert front.rejects["bad_mac"] == 1
        assert front.rejects["bad_token"] == 1
        c.close()
        bad.close()
    finally:
        h.stop()


def test_session_reuse_parking_and_roster_eviction():
    front = _small_front(park_after_s=0.15)
    h = FrontendHandle(front)
    try:
        c = GatewayClient("127.0.0.1", h.port, master=auth.master_key(seed=0))
        assert c.authenticate_many(range(4)) == 4
        rng = np.random.default_rng(1)
        for burst in range(3):                       # reuse, no re-handshake
            for gid in range(4):
                c.submit(gid, rng.normal(size=(8, DIM)).astype(np.float32))
            c.wait_all()
        assert front.table.handshakes_ok == 4        # one handshake each
        assert len(c.results) == 12
        assert all(len(st) == 8 for st, _, _ in c.results.values())

        _wait(lambda: front.table.stats()["parked"] == 4, timeout_s=10.0)
        # traffic on a parked session unparks it, no new handshake
        c.submit(1, rng.normal(size=(2, DIM)).astype(np.float32))
        c.wait_all()
        assert front.table.handshakes_ok == 4

        # roster swap retiring slot 1 evicts its session; its next
        # submit dies with BAD_STATE (no session), never a scored row
        roster2 = ServingRoster(member=np.r_[True, np.zeros(1, bool),
                                             np.ones(N - 2, bool)],
                                generation=np.zeros(N, np.int64))
        event = front.swap(roster=roster2)
        assert event["sessions_evicted"] == 1
        parsed = front.rows_parsed
        c._send(mux.pack_submit(1, 99, c.sessions[1].token,
                                np.zeros((2, DIM), np.float32)))
        _wait_reject(c, mux.REJ_BAD_STATE)
        assert front.rows_parsed == parsed
        c.close()
    finally:
        h.stop()


# -------------------- scoring equivalence through the stripe ------------ #


def test_frontend_striped_scoring_bit_identical_to_direct_router():
    """The frontend is auth + admission in FRONT of the net plane, not a
    new scoring path: the same seeded fleet scores the same rows to the
    same bits whether driven directly or through handshake + mux +
    stripe."""
    seed, reps, mb = 7, 2, 32
    rng = np.random.default_rng(99)
    rows = rng.normal(size=(48, DIM)).astype(np.float32)
    gid = 5

    direct = Router(build_synthetic_replicas(
        n_gateways=N, dim=DIM, replicas=reps, max_batch=mb, seed=seed,
        model_type="autoencoder"))
    res = direct.submit_many(rows, np.int32(gid))
    while not res.done:
        direct.poll()
    res.finalize()

    front = _small_front(replicas=reps, max_batch=mb, seed=seed,
                         calibrate=False, isolation_on=False)
    h = FrontendHandle(front)
    try:
        c = GatewayClient("127.0.0.1", h.port,
                          master=auth.master_key(seed=seed))
        assert c.authenticate(gid)
        seq = c.submit(gid, rows)
        c.wait_all()
        statuses, scores, _ = c.results[(gid, seq)]
        np.testing.assert_array_equal(statuses, res.statuses)
        np.testing.assert_array_equal(scores, res.scores)  # bitwise
        c.close()
    finally:
        h.stop()


def test_stripe_failover_zero_admitted_ticket_loss():
    """A member dying mid-flight: its in-flight pieces retry on the
    survivor; every admitted row still reaches exactly one terminal
    status."""
    reps = build_synthetic_replicas(n_gateways=N, dim=DIM, replicas=2,
                                    max_batch=16, seed=1,
                                    model_type="autoencoder")

    class Dying:
        def __init__(self, inner):
            self.inner = inner
            self.dead = False

        def __getattr__(self, name):
            return getattr(self.inner, name)

        def poll(self):
            if self.dead:
                raise RuntimeError("replica killed mid-flight")
            return self.inner.poll()

    dying = Dying(reps[0])
    stripe = FailoverStripe([dying, reps[1]])
    rng = np.random.default_rng(2)
    rows = rng.normal(size=(64, DIM)).astype(np.float32)
    blk = stripe.submit_many(rows, np.full(64, 3, np.int32))
    dying.dead = True                      # dies with pieces outstanding
    deadline = time.time() + 30
    while not blk.done:
        stripe.poll()
        assert time.time() < deadline
    st = stripe.stats()
    assert len(st["failover_events"]) >= 1 and st["alive"] == 1
    assert len(blk.scores) == 64 and np.isfinite(blk.scores).all()


# ------------------------- isolation (shed storm) ----------------------- #


def test_session_isolation_caps_flooder_not_honest():
    t = [0.0]
    iso = SessionIsolation(capacity_rows_per_sec=1000.0, session_share=0.1,
                           clock=lambda: t[0])
    roster = ServingRoster(member=np.ones(4, bool),
                           generation=np.zeros(4, np.int64))
    router = Router([InstantReplica(4)], roster=roster, isolation=iso,
                    clock=lambda: t[0])
    rows = np.zeros((500, DIM), np.float32)
    res = router.submit_many(rows, np.int32(1), session_key=1)
    res.finalize()
    flood_shed = int((res.statuses == wire.STATUS_SHED).sum())
    assert flood_shed >= 400                # capped at ~share * burst depth
    assert router.rows_isolated == flood_shed
    res2 = router.submit_many(rows[:10], np.int32(2), session_key=2)
    res2.finalize()
    assert int((res2.statuses == wire.STATUS_SHED).sum()) == 0


# ------------------------ two-class autoscale sizing --------------------- #


def test_plan_split_sizes_frontends_and_replicas_independently():
    fe = FrontendSpec(max_sessions=200_000, handshakes_per_sec=3000.0,
                      mux_rows_per_sec=500_000.0, usd_per_hour=0.05)
    be = [BackendSpec("cpu", rows_per_sec=50_000.0, usd_per_hour=0.10)]
    # the 1M-gateway shape: session-bound at near-zero rows/s — the
    # frontend count moves, the replica count does not
    p = plan_split(demand_rows_per_sec=1000.0, concurrent_sessions=1e6,
                   handshake_rate_per_sec=100.0, frontend=fe, backends=be)
    assert p["frontend_axis"] == "sessions"
    assert p["frontends"] == 9              # ceil(1e6 / (200k * 0.6))
    assert p["replicas"] == {"cpu": 1}
    # compute-bound shape: replicas move, frontends stay minimal
    q = plan_split(demand_rows_per_sec=120_000.0, concurrent_sessions=500,
                   handshake_rate_per_sec=10.0, frontend=fe, backends=be)
    assert q["frontends"] == 1 and q["replicas"]["cpu"] == 4
    assert q["frontend_axis"] == "mux_rows"
    assert q["usd_per_hour"] == pytest.approx(
        q["frontend_usd_per_hour"] + q["replica_usd_per_hour"])


def test_scale_down_requires_confirmation_ticks():
    t = [0.0]
    sc = SLOAutoscaler(budget_ms=25.0,
                       backends=[BackendSpec("cpu", rows_per_sec=10_000.0,
                                             usd_per_hour=0.1)],
                       cooldown_s=0.0, scale_down_confirm_ticks=3,
                       clock=lambda: t[0])
    cur = {"cpu": 4}

    def tick(arrival):
        t[0] += 1.0
        return sc.decide(arrival_rows_per_sec=arrival, p99_ms=None,
                         current=cur)

    assert tick(500.0).action == "hold"      # streak 1/3
    assert tick(500.0).action == "hold"      # streak 2/3
    assert "confirmation" in sc.decisions[-1].reason
    assert tick(30_000.0).action == "scale_up"   # burst resets the streak
    cur = {"cpu": 5}
    assert tick(500.0).action == "hold"
    assert tick(500.0).action == "hold"
    d = tick(500.0)                          # streak 3/3 -> confirmed
    assert d.action == "scale_down" and d.replicas == {"cpu": 1}
