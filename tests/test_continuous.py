"""Continuous-batching front tests (fedmse_tpu/serving/continuous.py):
ticket integrity and ordering under the forming/in-flight double buffer,
swap atomicity across all three hot-swap kinds (thresholds, checkpoint,
kNN bank — every submitted ticket scored exactly once, in order, under
the regime that admitted it), adaptive bucket selection, kNN bank
REFRESH + persistence, drift swap_recommended debounce, the engine's
dispatch/harvest split and zero-recompile swap_state, dense-vs-gather
routing parity, mesh-sharded serving parity, and the windowed wall
throughput fix in the sync batcher."""

import glob
import json
import os

import numpy as np
import pytest

import jax

from fedmse_tpu.knn import build_banks, load_bank, save_bank
from fedmse_tpu.models import init_stacked_params, make_model
from fedmse_tpu.serving import (ContinuousBatcher, DriftMonitor, MicroBatcher,
                                ServingEngine, fit_calibration,
                                fit_gateway_centroids)

pytestmark = pytest.mark.serve

DIM = 12
N = 3


def _setup(model_type="hybrid", seed=0, max_bucket=64, **kw):
    rng = np.random.default_rng(seed)
    model = make_model(model_type, DIM, shrink_lambda=1.0)
    params = init_stacked_params(model, jax.random.key(seed), N)
    train_x = rng.normal(size=(N, 60, DIM)).astype(np.float32)
    eng = ServingEngine.from_federation(
        model, model_type, params, train_x=train_x, max_bucket=max_bucket,
        **kw)
    valid_x = rng.normal(size=(N, 120, DIM)).astype(np.float32)
    cal = fit_calibration(eng, valid_x)
    rows = rng.normal(size=(400, DIM)).astype(np.float32)
    gws = rng.integers(0, N, 400).astype(np.int32)
    return model, params, train_x, eng, cal, rows, gws


# -------------------- ticket integrity and ordering -------------------- #

def test_continuous_scores_match_sync_in_order():
    """Every submitted ticket completes exactly once, in submission
    order, with the same scores the blocking engine produces — across
    size-triggered flushes, a mid-stream burst, and the drain tail."""
    _, _, _, eng, cal, rows, gws = _setup()
    front = ContinuousBatcher(eng, max_batch=32, latency_budget_ms=1e9,
                              calibration=cal)
    tks = [front.submit(rows[i], gws[i]) for i in range(100)]
    blk = front.submit_many(rows[100:300], gws[100:300])
    tks2 = [front.submit(rows[i], gws[i]) for i in range(300, 345)]
    front.drain()
    assert all(t.done for t in tks) and blk.done and all(
        t.done for t in tks2)
    got = np.concatenate([np.asarray([t.score for t in tks]), blk.scores,
                          np.asarray([t.score for t in tks2])])
    np.testing.assert_allclose(got, eng.score(rows[:345], gws[:345]),
                               atol=1e-5)
    st = front.stats()
    assert st["rows_served"] == st["rows_submitted"] == 345  # zero drops
    assert front.in_flight_rows == 0 and front.forming_rows == 0
    # TicketBlock is a real lazy sequence: len / index / iterate agree
    assert len(blk) == 200 and blk[0].done and blk[-1].done
    assert blk[3].score == pytest.approx(float(blk.scores[3]))
    assert sum(1 for _ in blk) == 200
    assert blk.verdicts is not None and blk.verdicts.shape == (200,)


def test_tickets_complete_one_flush_late_and_poll_harvests():
    _, _, _, eng, cal, rows, gws = _setup()
    front = ContinuousBatcher(eng, max_batch=8, latency_budget_ms=1e9)
    t1 = [front.submit(rows[i], gws[i]) for i in range(8)]
    # batch 1 dispatched (in flight) but NOT harvested yet: the double
    # buffer holds it until the next flush or a poll
    assert not t1[0].done and front.in_flight_rows == 8
    t2 = [front.submit(rows[i], gws[i]) for i in range(8, 16)]
    # flushing batch 2 harvested batch 1
    assert all(t.done for t in t1) and not t2[0].done
    # poll() harvests a ready in-flight batch without new traffic (the
    # wait is TIME-bounded, not iteration-bounded: a fixed poll count
    # races the async dispatch and flakes under host load)
    import time as _time
    deadline = _time.perf_counter() + 10.0
    while not front.poll() and _time.perf_counter() < deadline:
        pass
    assert all(t.done for t in t2)
    np.testing.assert_allclose(
        [t.score for t in t1 + t2], eng.score(rows[:16], gws[:16]),
        atol=1e-5)


# ------------------------------ hot swap ------------------------------- #

def test_threshold_swap_mid_stream_is_atomic_per_batch():
    """Verdicts use the calibration active at each batch's DISPATCH:
    batches in flight keep the old thresholds, batches formed after the
    swap use the new — no ticket is dropped or scored twice."""
    _, _, _, eng, cal, rows, gws = _setup()
    lo = cal.refit(0, np.asarray([-1e9]))  # g0 threshold -inf-ish: always
    for g in range(1, N):                  # flags; same for every gateway
        lo = lo.refit(g, np.asarray([-1e9]))
    front = ContinuousBatcher(eng, max_batch=16, latency_budget_ms=1e9,
                              calibration=cal)
    pre = [front.submit(rows[i], gws[i]) for i in range(24)]  # 16 flushed,
    event = front.swap(calibration=lo)                        # 8 forming
    post = [front.submit(rows[i], gws[i]) for i in range(24, 48)]
    front.drain()
    assert event["kinds"] == ["thresholds"]
    assert all(t.done for t in pre + post)
    # batch 1 (rows 0..15) dispatched under the ORIGINAL calibration
    want_pre = cal.verdicts(eng.score(rows[:16], gws[:16]), gws[:16])
    assert [t.verdict for t in pre[:16]] == list(want_pre)
    # everything dispatched after the swap flags unconditionally
    assert all(t.verdict for t in pre[16:] + post)
    # scores themselves are unaffected by a threshold swap
    np.testing.assert_allclose([t.score for t in pre + post],
                               eng.score(rows[:48], gws[:48]), atol=1e-5)
    assert front.stats()["rows_served"] == 48


def test_checkpoint_swap_mid_stream_zero_recompile():
    """A params swap takes effect at the next dispatch, leaves the
    in-flight batch on the old checkpoint, retraces nothing, and drops
    no tickets."""
    model, params, train_x, eng, cal, rows, gws = _setup()
    params2 = init_stacked_params(model, jax.random.key(9), N)
    cens2 = fit_gateway_centroids(model, params2, train_x)
    eng2 = ServingEngine.from_federation(model, "hybrid", params2,
                                         train_x=train_x, max_bucket=64)
    want_old = eng.score(rows[:64], gws[:64])
    want_new = eng2.score(rows[:64], gws[:64])

    front = ContinuousBatcher(eng, max_batch=16, latency_budget_ms=1e9)
    pre = [front.submit(rows[i], gws[i]) for i in range(16)]  # in flight
    cache = eng._score_fn._cache_size()
    event = front.swap(params=params2, centroids=cens2)
    post = [front.submit(rows[i], gws[i]) for i in range(16, 64)]
    front.drain()
    assert set(event["kinds"]) == {"params", "centroids"}
    assert eng._score_fn._cache_size() == cache  # pointer flip, no retrace
    np.testing.assert_allclose([t.score for t in pre], want_old[:16],
                               atol=1e-5)
    np.testing.assert_allclose([t.score for t in post], want_new[16:64],
                               atol=1e-5)
    assert front.stats()["rows_served"] == 64
    assert eng.swap_count == 1


def test_bank_swap_with_refresh_and_roundtrip(tmp_path):
    """score_kind='knn': build_banks(existing=...) reservoir-merges new
    normal latents into the resident bank, the result round-trips
    persistence exactly, and swapping it in mid-stream re-scores nothing
    already in flight."""
    rng = np.random.default_rng(3)
    model, params, train_x, eng, cal, rows, gws = _setup(
        "autoencoder", score_kind="knn", knn_bank_size=16)
    bank = eng.banks
    new_x = rng.normal(size=(N, 40, DIM)).astype(np.float32) + 0.5
    refreshed = build_banks(model, params, new_x, existing=bank, seed=7)
    assert refreshed.bank_size == bank.bank_size
    assert refreshed.num_gateways == N
    # refreshed slots come from (retained old slots) U (new latents)
    own = jax.tree.map(lambda t: t[0], params)
    lat_new = np.asarray(model.apply({"params": own}, new_x[0])[0])
    pool = np.concatenate(
        [np.asarray(bank.latents[0])[:int(bank.count[0])], lat_new])
    for r in np.asarray(refreshed.latents[0])[:int(refreshed.count[0])]:
        assert np.abs(pool - r).sum(axis=1).min() < 1e-5
    # ... and genuinely mix both sources at these sizes
    n_old = sum(1 for r in np.asarray(refreshed.latents[0])
                if np.abs(np.asarray(bank.latents[0])[:int(bank.count[0])]
                          - r).sum(axis=1).min() < 1e-5)
    assert 0 < n_old < refreshed.bank_size
    # persistence round-trip is exact
    path = save_bank(os.path.join(str(tmp_path), "bank.npz"), refreshed)
    back = load_bank(path)
    np.testing.assert_array_equal(np.asarray(back.latents),
                                  np.asarray(refreshed.latents))
    np.testing.assert_array_equal(np.asarray(back.count),
                                  np.asarray(refreshed.count))

    eng_new = ServingEngine(model, "autoencoder", params, banks=back,
                            score_kind="knn", max_bucket=64)
    want_old = eng.score(rows[:48], gws[:48])
    want_new = eng_new.score(rows[:48], gws[:48])
    front = ContinuousBatcher(eng, max_batch=16, latency_budget_ms=1e9)
    pre = [front.submit(rows[i], gws[i]) for i in range(16)]
    front.swap(banks=back)
    post = [front.submit(rows[i], gws[i]) for i in range(16, 48)]
    front.drain()
    np.testing.assert_allclose([t.score for t in pre], want_old[:16],
                               atol=1e-5)
    np.testing.assert_allclose([t.score for t in post], want_new[16:48],
                               atol=1e-5)
    assert front.stats()["rows_served"] == 48


def test_swap_rejects_foreign_payloads():
    model, params, train_x, eng, cal, rows, gws = _setup()
    front = ContinuousBatcher(eng, max_batch=16, calibration=cal)
    wrong = init_stacked_params(model, jax.random.key(1), N + 2)
    with pytest.raises(ValueError, match="swap params"):
        front.swap(params=wrong)
    import dataclasses
    bad_cal = dataclasses.replace(
        cal, thresholds=np.zeros(N + 2), mean=np.zeros(N + 2),
        std=np.zeros(N + 2), count=np.zeros(N + 2, np.int64))
    with pytest.raises(ValueError, match="calibration"):
        front.swap(calibration=bad_cal)
    with pytest.raises(ValueError, match="without kNN banks"):
        front.swap(banks=object())
    with pytest.raises(ValueError, match="nothing to swap"):
        front.swap()


def test_calibration_swap_does_not_seed_rebaselined_drift():
    """A batch in flight at swap(calibration=...) time was scored under
    the OLD regime: its scores must not be absorbed into the just-reset
    drift monitor (which would seed the new baseline with old-regime
    traffic and could re-recommend the swap that just happened)."""
    _, _, _, eng, cal, rows, gws = _setup()
    dm = DriftMonitor(cal, min_count=5, min_batches=2)
    front = ContinuousBatcher(eng, max_batch=16, latency_budget_ms=1e9,
                              calibration=cal, drift=dm)
    pre = [front.submit(rows[i], gws[i]) for i in range(16)]  # in flight
    front.swap(calibration=cal.refit(0, np.linspace(0, 1, 50)))
    assert dm.count.sum() == 0  # rebaselined
    post = [front.submit(rows[i], gws[i]) for i in range(16, 48)]
    front.drain()
    assert all(t.done for t in pre + post)
    # only the 32 post-swap rows reached the rebaselined monitor
    assert dm.count.sum() == 32


def test_submit_many_detaches_from_reused_caller_buffer():
    """The NIC-poll pattern: the caller refills its staging buffer after
    submit_many but before the window flushes — tickets must still score
    the bytes that were submitted, not the buffer's later content."""
    _, _, _, eng, cal, rows, gws = _setup()
    front = ContinuousBatcher(eng, max_batch=64, latency_budget_ms=1e9)
    buf = rows[:16].copy()
    gbuf = gws[:16].copy()
    want = eng.score(buf, gbuf)
    blk = front.submit_many(buf, gbuf)
    buf[:] = 1e6  # socket read overwrites the staging buffer
    gbuf[:] = 0
    front.drain()
    np.testing.assert_allclose(blk.scores, want, atol=1e-5)


def test_ticket_block_rejects_out_of_range_indices():
    _, _, _, eng, cal, rows, gws = _setup()
    front = ContinuousBatcher(eng, max_batch=64, latency_budget_ms=1e9)
    blk = front.submit_many(rows[:5], gws[:5])
    front.drain()
    assert blk[-1].score == blk[4].score
    with pytest.raises(IndexError):
        blk[5]
    with pytest.raises(IndexError):
        blk[-6]


# ------------------------- adaptive bucket pick ------------------------ #

def test_adaptive_bucket_tracks_arrival_rate():
    """Slow traffic settles on the largest bucket the rate fills within
    the budget (near-unpadded deadline dispatches); a traffic surge
    ramps the target back toward max_batch."""
    _, _, _, eng, cal, rows, gws = _setup()
    now = [0.0]
    front = ContinuousBatcher(eng, max_batch=64, latency_budget_ms=8.0,
                              clock=lambda: now[0])
    # 1 row per ms: the 8 ms budget holds ~8 rows
    i = 0
    for _ in range(40):
        front.submit(rows[i % 400], gws[i % 400]); i += 1
        now[0] += 0.001
    st = front.stats()
    assert st["target_bucket"] == 8  # largest pow2 the rate fills in-budget
    assert max(front.dispatch_batch_sizes) <= 16
    # surge: 16 rows per ms -> the EMA ramps the target to max_batch
    for _ in range(400):
        front.submit(rows[i % 400], gws[i % 400]); i += 1
        now[0] += 0.0000625
    assert front.stats()["target_bucket"] == 64
    front.drain()
    assert front.stats()["rows_served"] == front.stats()["rows_submitted"]


# ----------------------- drift swap recommendation --------------------- #

def test_drift_swap_recommended_is_debounced_and_rebaselines():
    """swap_recommended = drifted AND sustained min_batches updates —
    testable without an engine; rebaseline() restarts the moments."""
    _, _, _, eng, cal, rows, gws = _setup()
    dm = DriftMonitor(cal, z_threshold=3.0, min_count=10, min_batches=2)
    shifted = cal.mean[0] + 50.0 * max(cal.std[0], 1e-3)
    dm.update(np.full(20, shifted), np.zeros(20, np.int32))
    rep1 = dm.report()
    assert rep1["gateways"][0]["drifted"]
    assert not rep1["gateways"][0]["swap_recommended"]  # streak 1 < 2
    assert rep1["swap_recommended_gateways"] == []
    dm.update(np.full(20, shifted), np.zeros(20, np.int32))
    rep2 = dm.report()
    assert rep2["gateways"][0]["swap_recommended"]
    assert rep2["swap_recommended_gateways"] == [0]
    assert rep2["min_batches"] == 2
    json.dumps(rep2)
    # enough in-band traffic pulls the cumulative mean back and resets
    # the streak (the moments are lifetime Welford state, so one quiet
    # batch after a hard shift is NOT enough — by design)
    dm.update(np.full(10_000, float(cal.mean[0])), np.zeros(10_000,
                                                            np.int32))
    assert not dm.drifted().any() and not dm.swap_recommended().any()
    # rebaseline (the threshold-swap hook) restarts the live moments
    dm.update(np.full(20, shifted), np.zeros(20, np.int32))
    dm.rebaseline(cal.refit(0, np.full(50, shifted)))
    assert dm.count.sum() == 0 and not dm.drifted().any()
    with pytest.raises(ValueError, match="rebaseline"):
        import dataclasses
        dm.rebaseline(dataclasses.replace(
            cal, thresholds=np.zeros(N + 1), mean=np.zeros(N + 1),
            std=np.zeros(N + 1), count=np.zeros(N + 1, np.int64)))


# --------------------- engine: dispatch/harvest split ------------------ #

def test_engine_dispatch_harvest_equals_score():
    _, _, _, eng, cal, rows, gws = _setup()
    pend = eng.dispatch(rows[:20], gws[:20])
    got = pend.harvest()
    assert pend.is_ready()
    np.testing.assert_allclose(got, eng.score(rows[:20], gws[:20]),
                               atol=1e-5)
    assert got.dtype == np.float32 and got.shape == (20,)
    with pytest.raises(ValueError, match="at most one bucket"):
        eng.dispatch(rows[:65], gws[:65])  # max_bucket=64
    with pytest.raises(ValueError, match="gateway_ids"):
        eng.dispatch(rows[:4])


def test_engine_swap_state_validates_and_swaps():
    model, params, train_x, eng, cal, rows, gws = _setup()
    with pytest.raises(ValueError, match="nothing to swap"):
        eng.swap_state()
    with pytest.raises(ValueError, match="without kNN banks"):
        eng.swap_state(banks=object())
    p2 = init_stacked_params(model, jax.random.key(4), N)
    c2 = fit_gateway_centroids(model, p2, train_x)
    info = eng.swap_state(params=p2, centroids=c2)
    assert set(info["swapped"]) == {"params", "centroids"}
    eng_ref = ServingEngine.from_federation(model, "hybrid", p2,
                                            train_x=train_x, max_bucket=64)
    np.testing.assert_allclose(eng.score(rows[:32], gws[:32]),
                               eng_ref.score(rows[:32], gws[:32]),
                               atol=1e-5)


# ----------------------------- routing --------------------------------- #

@pytest.mark.parametrize("model_type", ["autoencoder", "hybrid"])
def test_dense_and_gather_routing_agree(model_type):
    """'dense' (compute-all-gateways + select) and 'gather' (per-row
    param gather) are the same math in different lowerings; scores agree
    to float tolerance at every bucket shape."""
    rng = np.random.default_rng(5)
    model = make_model(model_type, DIM, shrink_lambda=1.0)
    params = init_stacked_params(model, jax.random.key(5), N)
    train_x = rng.normal(size=(N, 40, DIM)).astype(np.float32)
    kw = dict(train_x=train_x, max_bucket=16)
    dense = ServingEngine.from_federation(model, model_type, params,
                                          routing="dense", **kw)
    gather = ServingEngine.from_federation(model, model_type, params,
                                           routing="gather", **kw)
    assert dense.routing == "dense" and gather.routing == "gather"
    rows = rng.normal(size=(37, DIM)).astype(np.float32)
    gws = rng.integers(0, N, 37).astype(np.int32)
    for n in (1, 3, 16, 37):
        np.testing.assert_allclose(dense.score(rows[:n], gws[:n]),
                                   gather.score(rows[:n], gws[:n]),
                                   atol=1e-5)
    # auto: dense for small federations, gather past the breakeven
    assert ServingEngine(model, "autoencoder", params).routing == "dense"
    big = jax.tree.map(
        lambda t: np.repeat(np.asarray(t), 12, axis=0), params)  # N=36
    assert ServingEngine(model, "autoencoder", big).routing == "gather"


def test_mesh_sharded_serving_matches_unsharded(mesh8):
    """mesh= places the gateway axis (divisible) or the row axis over
    all devices; scores equal the single-device engine at sharded and
    sub-device-count buckets alike."""
    rng = np.random.default_rng(6)
    n = 8  # divisible by the 8-device mesh: gateway-sharded state
    model = make_model("autoencoder", DIM, shrink_lambda=1.0)
    params = init_stacked_params(model, jax.random.key(6), n)
    plain = ServingEngine(model, "autoencoder", params, max_bucket=64)
    meshed = ServingEngine(model, "autoencoder", params, max_bucket=64,
                           mesh=mesh8)
    rows = rng.normal(size=(64, DIM)).astype(np.float32)
    gws = rng.integers(0, n, 64).astype(np.int32)
    for take in (64, 16, 3):  # sharded rows / sharded / replicated small
        np.testing.assert_allclose(meshed.score(rows[:take], gws[:take]),
                                   plain.score(rows[:take], gws[:take]),
                                   atol=1e-5)
    # the continuous front runs unchanged over a meshed engine
    front = ContinuousBatcher(meshed, max_batch=32, latency_budget_ms=1e9)
    tks = [front.submit(rows[i], gws[i]) for i in range(40)]
    front.drain()
    np.testing.assert_allclose([t.score for t in tks],
                               plain.score(rows[:40], gws[:40]), atol=1e-5)


# --------------------- sync batcher windowed wall ---------------------- #

def test_microbatcher_windowed_wall_reflects_recent_rate():
    """rows_per_sec_wall is windowed like the percentiles beside it: a
    long slow history no longer dilutes the recent rate (the lifetime
    quotient survives under _lifetime)."""
    _, _, _, eng, cal, rows, gws = _setup()
    now = [0.0]
    b = MicroBatcher(eng, max_batch=4, max_wait_ms=1e9,
                     clock=lambda: now[0], stats_window=8)
    # slow era: 4 rows over 100 seconds
    for i in range(4):
        b.submit(rows[i], gws[i]); now[0] += 25.0
    # fast era: 8 rows over 0.8 seconds (fills the 8-row window)
    for i in range(4, 12):
        b.submit(rows[i], gws[i]); now[0] += 0.1
    b.drain()
    st = b.stats()
    assert st["rows_served"] == 12
    # windowed: 8 recent rows over ~0.8 s ~ 10 rows/s
    assert st["rows_per_sec_wall"] == pytest.approx(8 / 0.8, rel=0.2)
    # lifetime: 12 rows over ~100.8 s ~ 0.12 rows/s
    assert st["rows_per_sec_wall_lifetime"] == pytest.approx(12 / 100.8,
                                                             rel=0.05)


# --------------------------- calibration refit ------------------------- #

def test_calibration_refit_builds_single_gateway_payload():
    _, _, _, eng, cal, rows, gws = _setup()
    fresh = np.linspace(0.0, 1.0, 101)
    new = cal.refit(1, fresh, percentile=90.0)
    assert new is not cal and new.num_gateways == cal.num_gateways
    assert new.thresholds[1] == pytest.approx(np.percentile(fresh, 90.0))
    assert new.mean[1] == pytest.approx(fresh.mean())
    assert new.count[1] == 101
    for g in (0, 2):  # other gateways untouched
        assert new.thresholds[g] == cal.thresholds[g]
        assert new.count[g] == cal.count[g]
    with pytest.raises(ValueError, match="at least one"):
        cal.refit(0, np.empty(0))


# ------------------------------ driver --------------------------------- #

def test_cli_serve_continuous(tmp_path):
    """--serve --serve-continuous: the smoke pass streams through the
    continuous front end to end (train -> checkpoint -> calibrate ->
    serve -> drift) and reports its stats."""
    from fedmse_tpu.config import DatasetConfig
    from fedmse_tpu.main import main as cli_main
    from tests.test_data import _write_client_csvs

    root = str(tmp_path / "shards")
    _write_client_csvs(root, 4, dim=6, n_normal=60, n_abnormal=24)
    cfg_path = os.path.join(root, "config.json")
    with open(cfg_path, "w") as f:
        json.dump(DatasetConfig.for_client_dirs(root, 4).to_json(), f)
    out = cli_main([
        "--dataset-config", cfg_path,
        "--model-types", "hybrid", "--update-types", "mse_avg",
        "--network-size", "4", "--dim-features", "6",
        "--epochs", "1", "--num-rounds", "1", "--batch-size", "8",
        "--checkpoint-dir", str(tmp_path / "ckpt"),
        "--experiment-name", "serve-c", "--serve", "--serve-rows", "256",
        "--serve-continuous", "--serve-max-batch", "64",
    ])
    smoke = out["serve_smoke"]
    assert smoke["front"] == "continuous"
    st = smoke["batcher"]
    assert st["front"] == "continuous"
    assert st["rows_served"] == smoke["rows"] > 0
    assert st["max_batch"] == 64
    assert st["latency_p99_ms"] > 0 and st["swaps"] == []
    assert "swap_recommended_gateways" in smoke["drift"]
    assert glob.glob(os.path.join(
        str(tmp_path / "ckpt"), "4", "serve-c", "0", "Serving", "*",
        "*_calibration.json"))
    json.dumps(smoke)
