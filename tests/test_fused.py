"""Fused-round path (federation/fused.py): the single-dispatch round and the
scan-over-rounds schedule must reproduce the unfused reference-control-flow
path exactly (tie-break disabled => both paths are deterministic)."""

import dataclasses

import numpy as np
import pytest

from fedmse_tpu.config import CompatConfig, ExperimentConfig
from fedmse_tpu.data import build_dev_dataset, stack_clients, synthetic_clients
from fedmse_tpu.federation import RoundEngine
from fedmse_tpu.models import make_model
from fedmse_tpu.utils.seeding import ExperimentRngs

DIM = 12
N = 4


def build_engine(fused: bool, update_type: str = "mse_avg",
                 model_type: str = "hybrid", pad_to: int = None, **cfg_kw):
    cfg = ExperimentConfig(
        dim_features=DIM, network_size=N, epochs=2, batch_size=8,
        compat=CompatConfig(vote_tie_break=False), **cfg_kw)
    clients = synthetic_clients(n_clients=N, dim=DIM, n_normal=120,
                                n_abnormal=60)
    rngs = ExperimentRngs(run=0)
    dev_x = build_dev_dataset(clients, rngs.data_rng)
    data = stack_clients(clients, dev_x, cfg.batch_size, pad_clients_to=pad_to)
    m = make_model(model_type, DIM, shrink_lambda=cfg.shrink_lambda)
    return RoundEngine(m, cfg, data, n_real=N, rngs=rngs,
                       model_type=model_type, update_type=update_type,
                       fused=fused)


def assert_results_match(a, b):
    assert a.selected == b.selected
    assert a.aggregator == b.aggregator
    np.testing.assert_allclose(a.client_metrics, b.client_metrics,
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(a.min_valid, b.min_valid, rtol=1e-4, atol=1e-5)
    rows_a = [(r["client_id"], r["rejected_updates"]) for r in a.verification_results]
    rows_b = [(r["client_id"], r["rejected_updates"]) for r in b.verification_results]
    assert rows_a == rows_b
    if a.agg_weights is not None:
        np.testing.assert_allclose(a.agg_weights, b.agg_weights,
                                   rtol=1e-4, atol=1e-6)


@pytest.mark.parametrize("update_type", ["avg", "fedprox", "mse_avg"])
def test_fused_round_matches_unfused(update_type):
    ref = build_engine(fused=False, update_type=update_type)
    fus = build_engine(fused=True, update_type=update_type)
    for r in range(3):
        res_ref = ref.run_round(r)
        res_fus = fus.run_round(r)
        assert_results_match(res_ref, res_fus)
    np.testing.assert_array_equal(ref.host.aggregation_count,
                                  fus.host.aggregation_count)


def test_fused_scan_matches_per_round():
    """run_rounds (one dispatch for the whole schedule) == per-round fused."""
    a = build_engine(fused=True)
    b = build_engine(fused=True)
    res_a = [a.run_round(r) for r in range(3)]
    res_b = b.run_rounds(0, 3)
    for ra, rb in zip(res_a, res_b):
        assert_results_match(ra, rb)


def test_program_cache_shares_and_separates():
    """Identical engine configs share ONE program set (the cache that makes
    sweep runs after the first compile-free); any config field a builder
    consumes must be part of the cache key — differing lr must NOT share.
    Canary for future builder parameters forgotten in _engine_programs."""
    a = build_engine(fused=True)
    b = build_engine(fused=True)
    assert a.train_all is b.train_all
    assert a.evaluate_all is b.evaluate_all
    assert a.tx is b.tx  # shared transform => interchangeable opt states

    cfg_fast = dataclasses.replace(a.cfg, lr_rate=1e-2)
    c = RoundEngine(a.model, cfg_fast, a.data, n_real=N,
                    rngs=ExperimentRngs(run=0), model_type="hybrid",
                    update_type="mse_avg", fused=True)
    assert c.train_all is not a.train_all
    ra = a.run_round(0, selected=[0, 2])
    rc = c.run_round(0, selected=[0, 2])
    # different lr must actually train differently
    assert not np.allclose(ra.min_valid, rc.min_valid, equal_nan=True)


def test_whole_round_compact_matches_dense():
    """compact_cohort toggles the gather/scatter strategy in training AND
    fed_mse_avg scoring; a full fused round must produce the same elected
    aggregator and near-ulp-identical metrics/state either way (the bench's
    run-to-run AUC wiggle across recompiles is chaotic amplification of
    ulp noise, not a semantic difference — this pins the semantics)."""
    import jax
    dense = build_engine(fused=True, compact_cohort=False)
    compact = build_engine(fused=True, compact_cohort=True)
    for r, sel in enumerate(([0, 2], [1, 3])):
        rd = dense.run_round_fused(r, selected=sel)
        rc = compact.run_round_fused(r, selected=sel)
        assert rd.aggregator == rc.aggregator
        np.testing.assert_allclose(rd.client_metrics, rc.client_metrics,
                                   rtol=1e-4, atol=1e-5)
    for d, c in zip(jax.tree.leaves(jax.device_get(dense.states.params)),
                    jax.tree.leaves(jax.device_get(compact.states.params))):
        np.testing.assert_allclose(d, c, atol=1e-6)


def test_fused_with_padded_clients():
    fus = build_engine(fused=True, pad_to=8)
    res = fus.run_rounds(0, 2)
    assert res[-1].client_metrics.shape == (N,)
    assert np.all(np.isfinite(res[-1].client_metrics))
    assert res[-1].aggregator in res[-1].selected


def test_fused_quota_exhaustion():
    """Once every client hit the aggregation quota, no aggregator is found
    (reference: every voter returns None, main.py:284-288)."""
    fus = build_engine(fused=True)
    fus.host.aggregation_count[:] = fus.cfg.max_aggregation_threshold
    res = fus.run_round(0)
    assert res.aggregator is None
    assert res.mse_scores is None
    assert res.verification_results == []


def test_hardened_clean_run_identical_to_reference_mode():
    """--hardened-verification must be invisible on an honest federation:
    same selections, same aggregators, same metrics, zero rejections in
    BOTH modes over a multi-round schedule (the gates differ only in what
    they reject — a clean run offers nothing to reject). The engine-level
    twin of the unit-level honest-aggregate test."""
    ref = build_engine(fused=True)
    hard = build_engine(fused=True, hardened_verification=True)
    res_ref = ref.run_rounds(0, 3)
    res_hard = hard.run_rounds(0, 3)
    for ra, rb in zip(res_ref, res_hard):
        assert_results_match(ra, rb)
    assert all(r["rejected_updates"] == 0
               for res in res_hard for r in res.verification_results)
    # and the two modes must NOT share a verify program (cache key)
    assert ref.verify is not hard.verify


def test_flatten_optimizer_is_numerically_equivalent():
    """cfg.flatten_optimizer wraps Adam in optax.flatten — one fused
    vector update instead of 12 per-leaf ops per serial step. Adam is
    elementwise, so results must match the default layout numerically
    (same selections, aggregators, metrics) over a multi-round schedule;
    only the opt_state layout differs."""
    ref = build_engine(fused=True)
    flat = build_engine(fused=True, flatten_optimizer=True)
    res_ref = ref.run_rounds(0, 3)
    res_flat = flat.run_rounds(0, 3)
    for ra, rb in zip(res_ref, res_flat):
        assert_results_match(ra, rb)
    # different transforms must not share a program set
    assert ref.train_all is not flat.train_all
