"""Native IO runtime (native/fedmse_io.cpp + data/fast_csv.py): parsed floats
must match pandas bit-for-bit (both parse to float64), including header
detection, CRLF endings, scientific notation, and multi-file concat."""

import os

import numpy as np
import pandas as pd
import pytest

from fedmse_tpu.data.fast_csv import (native_available, read_csv_f64,
                                      read_dir_f64)
from fedmse_tpu.data.loader import load_data

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="native IO library unavailable")


def write(path, text):
    with open(path, "w") as f:
        f.write(text)


def test_basic_parse(tmp_path):
    p = tmp_path / "a.csv"
    write(p, "1.5,2.0,-3.25\n4.0,5e-3,6.0\n")
    out = read_csv_f64(str(p))
    np.testing.assert_array_equal(
        out, np.array([[1.5, 2.0, -3.25], [4.0, 5e-3, 6.0]], np.float64))


def test_header_detected_and_skipped(tmp_path):
    p = tmp_path / "a.csv"
    write(p, "col_a,col_b,col_c\n1.0,2.0,3.0\n")
    out = read_csv_f64(str(p))
    assert out.shape == (1, 3)
    np.testing.assert_array_equal(out[0], [1.0, 2.0, 3.0])


def test_crlf_and_no_trailing_newline(tmp_path):
    p = tmp_path / "a.csv"
    write(p, "1.0,2.0\r\n3.0,4.0")
    out = read_csv_f64(str(p))
    np.testing.assert_array_equal(
        out, np.array([[1, 2], [3, 4]], np.float64))


def test_scientific_notation_matches_pandas(tmp_path, rng):
    vals = rng.standard_normal((50, 7)) * 10.0 ** rng.integers(-12, 12, (50, 7))
    p = tmp_path / "a.csv"
    pd.DataFrame(vals).to_csv(p, header=False, index=False)
    out = read_csv_f64(str(p))
    want = pd.read_csv(p, header=None, float_precision="round_trip").values
    np.testing.assert_array_equal(out, want)


def test_read_dir_concatenates_sorted(tmp_path):
    write(tmp_path / "b.csv", "3.0,4.0\n")
    write(tmp_path / "a.csv", "1.0,2.0\n")
    out = read_dir_f64(str(tmp_path))
    np.testing.assert_array_equal(out, np.array([[1, 2], [3, 4]], np.float64))


def test_load_data_uses_native_and_matches_pandas(tmp_path, rng):
    vals = rng.standard_normal((30, 5))
    pd.DataFrame(vals).to_csv(tmp_path / "data.csv", header=False, index=False)
    native = load_data(str(tmp_path), use_native=True)
    fallback = load_data(str(tmp_path), use_native=False)
    np.testing.assert_array_equal(native.values, fallback.values)


def test_explicit_header_disables_native(tmp_path):
    # an explicit header index is a pandas-only contract (loader.py)
    write(tmp_path / "data.csv", "9.0,9.0\n1.0,2.0\n")
    out = load_data(str(tmp_path), header=0, use_native=True)
    assert len(out) == 1  # pandas consumed the first row as the header


def test_malformed_falls_back_to_pandas(tmp_path):
    # a ragged file the native parser rejects: load_data must still return
    write(tmp_path / "data.csv", "1.0,2.0\n3.0\n")
    out = load_data(str(tmp_path), use_native=True)
    assert len(out) == 2  # pandas parses ragged as NaN-padded or raises later


def test_wide_rows_rejected_by_native(tmp_path):
    # wider-than-first rows must NOT silently truncate: native rejects,
    # load_data falls back to pandas
    write(tmp_path / "a.csv", "1.0,2.0\n3.0,4.0,5.0\n")
    with pytest.raises(RuntimeError):
        read_csv_f64(str(tmp_path / "a.csv"))


def test_header_consistency_with_fallback(tmp_path):
    # load_data must return the same thing whether or not the native lib is
    # present: header-bearing files go through pandas on both paths
    write(tmp_path / "data.csv", "h1,h2\n1.0,2.0\n")
    native = load_data(str(tmp_path), use_native=True)
    fallback = load_data(str(tmp_path), use_native=False)
    assert len(native) == len(fallback) == 2  # header row parsed as data
