"""Test configuration: force an 8-device virtual CPU platform so multi-client
sharding paths are exercised without TPU hardware (SURVEY.md §4: the reference
'simulates multi-node without a cluster'; we do the same at the XLA level).

The container's sitecustomize registers the axon TPU backend in EVERY python
process (and the axon hook initializes it even under JAX_PLATFORMS=cpu, which
can block on the device tunnel). Tests must be hermetic and parallel-safe, so
we deregister the axon backend factory before any backend is initialized.
"""

import os
import sys

xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

# Persistent XLA compilation cache (VERDICT r2 #7): the suite is dominated by
# XLA compiles of the driver/fused/parallel round programs (~9 min cold);
# with a warm cache the same suite runs in a fraction of that. The cache dir
# survives across pytest invocations on this machine; the 2-process multihost
# workers inherit it through the environment (concurrent writers are safe —
# entries land via atomic rename). Warm floor on this 1-core box is ~6.5 min:
# the residual is Python-side tracing/lowering of the many distinct fused
# round programs, which jax cannot cache across processes.
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from fedmse_tpu.utils.platform import (enable_compilation_cache,  # noqa: E402
                                       force_cpu_platform)

enable_compilation_cache()  # before any jax import reads the env

force_cpu_platform()  # deregister the sitecustomize TPU tunnel pre-init

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)


@pytest.fixture(scope="session")
def mesh8():
    """The 8-virtual-device CPU `clients` mesh, session-shared.

    This conftest already forces `--xla_force_host_platform_device_count=8`
    before any backend initializes (top of file), so sharding tests should
    take this fixture instead of re-deriving the mesh or hand-rolling a
    skipif — it skips cleanly on the rare box where the virtual platform
    could not be realized."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual CPU devices "
                    "(XLA_FLAGS=--xla_force_host_platform_device_count=8)")
    from fedmse_tpu.parallel import client_mesh

    return client_mesh(8)
