"""Test configuration: force an 8-device virtual CPU platform so multi-client
sharding paths are exercised without TPU hardware (SURVEY.md §4: the reference
'simulates multi-node without a cluster'; we do the same at the XLA level).

The container's sitecustomize registers the axon TPU backend in EVERY python
process (and the axon hook initializes it even under JAX_PLATFORMS=cpu, which
can block on the device tunnel). Tests must be hermetic and parallel-safe, so
we deregister the axon backend factory before any backend is initialized.
"""

import os
import sys

xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from fedmse_tpu.utils.platform import force_cpu_platform  # noqa: E402

force_cpu_platform()  # deregister the sitecustomize TPU tunnel pre-init

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
