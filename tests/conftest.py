"""Test configuration: force an 8-device virtual CPU platform so multi-client
sharding paths are exercised without TPU hardware (SURVEY.md §4: the reference
'simulates multi-node without a cluster'; we do the same at the XLA level).

The container's sitecustomize registers the axon TPU backend in EVERY python
process (and the axon hook initializes it even under JAX_PLATFORMS=cpu, which
can block on the device tunnel). Tests must be hermetic and parallel-safe, so
we deregister the axon backend factory before any backend is initialized.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

import jax  # noqa: E402
import jax._src.xla_bridge as _xb  # noqa: E402

_xb._backend_factories.pop("axon", None)  # sitecustomize-registered TPU tunnel
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
