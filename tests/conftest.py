"""Test configuration: force an 8-device virtual CPU platform so multi-client
sharding paths are exercised without TPU hardware (SURVEY.md §4: the reference
'simulates multi-node without a cluster'; we do the same at the XLA level).

The container's sitecustomize registers the axon TPU backend in EVERY python
process (and the axon hook initializes it even under JAX_PLATFORMS=cpu, which
can block on the device tunnel). Tests must be hermetic and parallel-safe, so
we deregister the axon backend factory before any backend is initialized.
"""

import os
import sys

xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

# Persistent XLA compilation cache (VERDICT r2 #7): the suite is dominated by
# XLA compiles of the driver/fused/parallel round programs (~9 min cold);
# with a warm cache the same suite runs in a fraction of that. The cache dir
# survives across pytest invocations on this machine; the 2-process multihost
# workers inherit it through the environment (concurrent writers are safe —
# entries land via atomic rename). Warm floor on this 1-core box is ~6.5 min:
# the residual is Python-side tracing/lowering of the many distinct fused
# round programs, which jax cannot cache across processes.
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from fedmse_tpu.utils.platform import (enable_compilation_cache,  # noqa: E402
                                       force_cpu_platform)

enable_compilation_cache()  # before any jax import reads the env

force_cpu_platform()  # deregister the sitecustomize TPU tunnel pre-init

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)


@pytest.fixture(scope="session")
def two_process_outputs(tmp_path_factory):
    """ONE hardened 2-process worker-pair spawn (mode 'both': federated
    round, mid-chunk early stop, host-sharded pod tier) serving every
    two-process assertion in the suite (test_parallel.py multi-host tests,
    test_podscale.py). Session-scoped and routed through
    tests/multihost_launcher.py — fresh coordinator port per attempt plus a
    bounded whole-pair retry — so the 3 in-suite environment flakes
    documented in PR 11 (port steal between bind-close and coordinator
    bind; cold-start blowing the fixed timeout under suite load) cannot
    surface as tier-1 errors. Yields `.outs` (each process's combined
    stdout+stderr) and `.outdir` (PODSCALE_OUTDIR: pod results + the
    host-sharded checkpoint for cross-layout restores)."""
    import collections
    import os

    from multihost_launcher import launch_worker_pair

    outdir = tmp_path_factory.mktemp("podscale")
    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    outs = launch_worker_pair(worker, args=("both",),
                              extra_env={"PODSCALE_OUTDIR": str(outdir)})
    Run = collections.namedtuple("TwoProcessRun", ["outs", "outdir"])
    return Run(outs=outs, outdir=outdir)


@pytest.fixture(scope="session")
def mesh8():
    """The 8-virtual-device CPU `clients` mesh, session-shared.

    This conftest already forces `--xla_force_host_platform_device_count=8`
    before any backend initializes (top of file), so sharding tests should
    take this fixture instead of re-deriving the mesh or hand-rolling a
    skipif — it skips cleanly on the rare box where the virtual platform
    could not be realized."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual CPU devices "
                    "(XLA_FLAGS=--xla_force_host_platform_device_count=8)")
    from fedmse_tpu.parallel import client_mesh

    return client_mesh(8)
