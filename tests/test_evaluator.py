"""Evaluator tests: AE/hybrid paths vs reference formulas, fused-path
equivalence, single-model Evaluator API parity."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fedmse_tpu.evaluation import Evaluator, make_evaluate_all
from fedmse_tpu.models import make_model, init_stacked_params, init_client_params

DIM = 12


def _data(n_clients=3, t=90, s=60, seed=0):
    rng = np.random.default_rng(seed)
    test_x = jnp.asarray(rng.normal(size=(n_clients, t, DIM)).astype(np.float32))
    test_y = jnp.asarray((rng.random((n_clients, t)) < 0.4).astype(np.float32))
    test_m = jnp.asarray((rng.random((n_clients, t)) < 0.9).astype(np.float32))
    train_xb = jnp.asarray(rng.normal(size=(n_clients, 6, 10, DIM)).astype(np.float32))
    train_mb = jnp.ones((n_clients, 6, 10))
    return test_x, test_m, test_y, train_xb, train_mb


@pytest.mark.parametrize("model_type", ["autoencoder", "hybrid"])
def test_evaluate_all_matches_reference_math(model_type):
    """Vectorized evaluator == per-client sklearn/scipy reference computation
    (reference evaluator.py:52-127)."""
    from sklearn.metrics import roc_auc_score
    from sklearn import preprocessing
    import scipy.spatial

    model = make_model(model_type, DIM, shrink_lambda=1.0)
    params = init_stacked_params(model, jax.random.key(0), 3)
    test_x, test_m, test_y, train_xb, train_mb = _data()
    got = np.asarray(make_evaluate_all(model, model_type)(
        params, test_x, test_m, test_y, train_xb, train_mb))

    for i in range(3):
        p = jax.tree.map(lambda t: t[i], params)
        mask = np.asarray(test_m[i]) > 0
        tx = np.asarray(test_x[i])[mask]
        ty = np.asarray(test_y[i])[mask]
        latent, recon = model.apply({"params": p}, jnp.asarray(tx))
        if model_type == "autoencoder":
            scores = np.mean((tx - np.asarray(recon)) ** 2, axis=1)
        else:
            train_flat = np.asarray(train_xb[i]).reshape(-1, DIM)
            tl, _ = model.apply({"params": p}, jnp.asarray(train_flat))
            scaler = preprocessing.StandardScaler().fit(np.asarray(tl))
            scores = scipy.spatial.distance.cdist(
                scaler.transform(np.asarray(latent)),
                np.zeros((1, np.asarray(latent).shape[1]))).mean(axis=1)
        want = roc_auc_score(ty, scores)
        assert got[i] == pytest.approx(want, abs=1e-5)


@pytest.mark.parametrize("model_type", ["autoencoder", "hybrid"])
def test_evaluate_all_classification_triple_matches_sklearn(model_type):
    """metric='classification' returns [N, 3] f1/precision/recall — the
    reference's calculate_classification_metric returns all three
    (evaluator.py:42-47); the batch path returning f1 only was VERDICT
    'missing' #4. Parity against sklearn at the reference's 0.5 score
    threshold, per client, padded rows excluded."""
    from sklearn.metrics import f1_score, precision_score, recall_score

    model = make_model(model_type, DIM, shrink_lambda=1.0)
    params = init_stacked_params(model, jax.random.key(0), 3)
    test_x, test_m, test_y, train_xb, train_mb = _data()
    got = np.asarray(make_evaluate_all(model, model_type,
                                       metric="classification")(
        params, test_x, test_m, test_y, train_xb, train_mb))
    assert got.shape == (3, 3)

    from fedmse_tpu.models.centroid import fit_centroid
    for i in range(3):
        p = jax.tree.map(lambda t: t[i], params)
        mask = np.asarray(test_m[i]) > 0
        tx = np.asarray(test_x[i])[mask]
        ty = np.asarray(test_y[i])[mask]
        latent, recon = model.apply({"params": p}, jnp.asarray(tx))
        if model_type == "autoencoder":
            scores = np.mean((tx - np.asarray(recon)) ** 2, axis=1)
        else:
            train_flat = np.asarray(train_xb[i]).reshape(-1, DIM)
            tl, _ = model.apply({"params": p}, jnp.asarray(train_flat))
            scores = np.asarray(fit_centroid(tl).get_density(latent))
        pred = (np.nan_to_num(scores) > 0.5).astype(np.float32)
        for col, fn in enumerate((f1_score, precision_score, recall_score)):
            want = fn(ty, pred, zero_division=0)
            assert got[i, col] == pytest.approx(want, abs=1e-5), \
                (model_type, i, col)


@pytest.mark.parametrize("fused", ["xla", "interpret"])
@pytest.mark.parametrize("model_type", ["autoencoder", "hybrid"])
def test_fused_eval_matches_plain(model_type, fused):
    """'interpret' drives the actual pallas_call (in interpret mode) through
    the vmapped, jitted evaluator — the same batching path the TPU kernel
    takes with fused='pallas'."""
    model = make_model(model_type, DIM, shrink_lambda=1.0)
    params = init_stacked_params(model, jax.random.key(1), 3)
    data = _data(seed=1)
    plain = np.asarray(make_evaluate_all(model, model_type, fused="off")(params, *data))
    got = np.asarray(make_evaluate_all(model, model_type, fused=fused)(params, *data))
    np.testing.assert_allclose(plain, got, atol=1e-5)


def test_single_evaluator_api_parity():
    """Evaluator returns a scalar for AE, (auc, latents, labels) for hybrid
    (reference evaluator.py:64-74, :119), and a float for 'time'."""
    rng = np.random.default_rng(2)
    test_x = rng.normal(size=(80, DIM)).astype(np.float32)
    test_y = (rng.random(80) < 0.5).astype(np.float32)
    train_x = rng.normal(size=(50, DIM)).astype(np.float32)

    ae = make_model("autoencoder", DIM)
    p = init_client_params(ae, jax.random.key(0))
    auc = Evaluator(ae, p, "autoencoder", "AUC").evaluate(test_x, test_y)
    assert isinstance(auc, float) and 0 <= auc <= 1

    sae = make_model("hybrid", DIM, shrink_lambda=1.0)
    p = init_client_params(sae, jax.random.key(0))
    out = Evaluator(sae, p, "hybrid", "AUC").evaluate(test_x, test_y, train_x)
    assert isinstance(out, tuple) and len(out) == 3
    auc, latents, labels = out
    assert 0 <= auc <= 1 and latents.shape == (80, 7) and labels.shape == (80,)

    t = Evaluator(sae, p, "hybrid", "time").evaluate(test_x, test_y, train_x)
    assert isinstance(t, float) and t >= 0

    f1 = Evaluator(ae, p, "autoencoder", "classification").evaluate(test_x, test_y)
    assert isinstance(f1, float) and 0 <= f1 <= 1


def test_time_metric_excludes_compilation():
    """metric='time' must report steady-state latency, not first-call
    tracing + XLA compilation (VERDICT r2 weak #5). The whole evaluate()
    call pays the compile; the RETURNED number must be far smaller."""
    import time as _time
    rng = np.random.default_rng(3)
    test_x = rng.normal(size=(400, DIM)).astype(np.float32)
    test_y = (rng.random(400) < 0.5).astype(np.float32)
    train_x = rng.normal(size=(200, DIM)).astype(np.float32)

    sae = make_model("hybrid", DIM, shrink_lambda=1.0)
    p = init_client_params(sae, jax.random.key(4))
    ev = Evaluator(sae, p, "hybrid", "time")
    t0 = _time.perf_counter()
    t_steady = ev.evaluate(test_x, test_y, train_x)
    wall = _time.perf_counter() - t0
    assert t_steady > 0
    # wall includes compile + warmup + reps*t_steady; compile alone is
    # tens of ms while one steady pass at this size is well under 5 ms.
    assert t_steady * 5 < wall


def test_evaluate_all_time_warmup_keeps_compile_out_of_latency():
    """Regression pin for the latency_all warmup (evaluator.py: the jitted
    scorer's first call pays tracing + XLA compilation; the warmup call
    absorbs it so the measured per-client latencies are steady-state).
    First-call cost vs steady-state must differ by far more than 10x, so
    if the warmup ever regresses, the returned latencies jump by orders
    of magnitude and the wall/steady ratio here collapses below the bar."""
    import time as _time
    model = make_model("hybrid", DIM, shrink_lambda=1.0)
    params = init_stacked_params(model, jax.random.key(6), 2)
    # distinctive shapes: this program must not be pre-compiled (in this
    # process) by another test
    data = _data(n_clients=2, t=407, s=61, seed=6)
    latency_all = make_evaluate_all(model, "hybrid", metric="time")
    t0 = _time.perf_counter()
    lat = np.asarray(latency_all(params, *data))
    wall = _time.perf_counter() - t0
    assert lat.shape == (2,) and np.all(lat > 0)
    # wall includes the first (warmup/compile) call plus 2 clients x
    # latency_reps steady passes; steady state at this size is sub-ms
    # while tracing+compile alone is tens of ms even on a warm disk
    # cache — the >10x gap is what the warmup preserves
    assert wall > 10 * lat.max(), (wall, lat)
    # and the reported latencies are absolutely steady-state-sized: one
    # pass at this size is sub-ms; tracing + compile alone is tens of ms,
    # so a latency that accidentally included the first call would blow
    # far past this bound
    assert lat.max() < 0.05, lat


def test_evaluate_all_time_metric_per_client():
    """The vectorized evaluator's 'time' mode returns one steady-state
    latency per client (reference evaluator.py:99-108 had no vectorized
    counterpart — VERDICT r2 missing #3)."""
    model = make_model("hybrid", DIM, shrink_lambda=1.0)
    params = init_stacked_params(model, jax.random.key(5), 3)
    data = _data(seed=5)
    lat = make_evaluate_all(model, "hybrid", metric="time")(params, *data)
    assert lat.shape == (3,)
    assert np.all(lat > 0) and np.all(lat < 1.0)


def test_time_metric_rejected_by_fused_engine():
    """Host-side latency cannot be traced into the fused round program; the
    engine must fail fast, not at trace time inside XLA."""
    from fedmse_tpu.config import ExperimentConfig
    from fedmse_tpu.data import synthetic_clients, build_dev_dataset, stack_clients
    from fedmse_tpu.federation import RoundEngine
    from fedmse_tpu.utils.seeding import ExperimentRngs

    cfg = ExperimentConfig(dim_features=DIM, network_size=3, epochs=1,
                           batch_size=4, metric="time")
    rngs = ExperimentRngs(run=0)
    clients = synthetic_clients(n_clients=3, dim=DIM, n_normal=24, n_abnormal=8)
    data = stack_clients(clients, build_dev_dataset(clients, rngs.data_rng),
                         cfg.batch_size)
    model = make_model("hybrid", DIM, shrink_lambda=1.0)
    with pytest.raises(ValueError, match="time"):
        RoundEngine(model, cfg, data, n_real=3, rngs=rngs,
                    model_type="hybrid", update_type="avg", fused=True)
