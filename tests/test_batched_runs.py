"""Batched multi-run execution (federation/batched.py): R runs-axis-batched
federations must reproduce R sequential runs exactly — per-run metric
streams, election outcomes, early-stop rounds, and the ResultsWriter
artifact layout. Sequential mode is the correctness oracle (ISSUE 1)."""

import json
import os
import pickle

import numpy as np
import pytest

from fedmse_tpu.checkpointing import ResultsWriter
from fedmse_tpu.config import CompatConfig, ExperimentConfig
from fedmse_tpu.data import build_dev_dataset, stack_clients, synthetic_clients
from fedmse_tpu.federation import BatchedRunEngine, RoundEngine
from fedmse_tpu.main import (GlobalEarlyStop, run_batched_combination,
                             run_combination)
from fedmse_tpu.models import make_model
from fedmse_tpu.utils.seeding import ExperimentRngs, batched_run_keys, make_run_rngs

DIM = 12
N = 4
RUNS = 3


def build_cfg(**kw):
    kw.setdefault("num_rounds", 3)
    return ExperimentConfig(
        dim_features=DIM, network_size=N, epochs=2, batch_size=8,
        num_runs=RUNS, compat=CompatConfig(vote_tie_break=False), **kw)


def build_data(cfg):
    clients = synthetic_clients(n_clients=N, dim=DIM, n_normal=120,
                                n_abnormal=60)
    dev_x = build_dev_dataset(clients, ExperimentRngs(run=0).data_rng)
    return stack_clients(clients, dev_x, cfg.batch_size)


def test_batched_run_keys_match_sequential_streams():
    """Column r of the batched key array must be bit-identical to run r's
    own sequential next_jax() draws (the stream-preservation contract)."""
    import jax
    batched = make_run_rngs(RUNS)
    keys = batched_run_keys(batched, 4)
    for r in range(RUNS):
        solo = ExperimentRngs(run=r)
        for i in range(4):
            np.testing.assert_array_equal(
                jax.random.key_data(keys[i, r]),
                jax.random.key_data(solo.next_jax()))


def test_batched_chunk_matches_sequential_runs():
    """One batched dispatch of K rounds x R runs == R sequential fused
    schedules with the same seeds: selections, aggregators, metric streams,
    min-valid curves (tolerance 1e-5; bitwise on CPU in practice)."""
    cfg = build_cfg()
    data = build_data(cfg)
    model = make_model("hybrid", DIM, shrink_lambda=cfg.shrink_lambda)

    seq = {}
    for r in range(RUNS):
        eng = RoundEngine(model, cfg, data, n_real=N,
                          rngs=ExperimentRngs(run=r), model_type="hybrid",
                          update_type="mse_avg", fused=True)
        seq[r] = eng.run_rounds(0, cfg.num_rounds)

    bat = BatchedRunEngine(model, cfg, data, n_real=N, runs=RUNS,
                           model_type="hybrid", update_type="mse_avg")
    outs, schedule, _ = bat.run_schedule_chunk(0, cfg.num_rounds,
                                               np.ones(RUNS, bool))
    for i in range(cfg.num_rounds):
        for r in range(RUNS):
            res = bat.process_round(r, i, schedule[i][r], outs, i)
            ref = seq[r][i]
            assert res.selected == ref.selected
            assert res.aggregator == ref.aggregator
            np.testing.assert_allclose(res.client_metrics,
                                       ref.client_metrics,
                                       rtol=1e-5, atol=1e-6)
            np.testing.assert_allclose(res.min_valid, ref.min_valid,
                                       rtol=1e-5, atol=1e-6)
    finals = bat.evaluate_final()
    assert finals.shape == (RUNS, N)


def _read_json_lines(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _walk_files(root):
    out = {}
    for d, _, files in os.walk(root):
        for name in files:
            p = os.path.join(d, name)
            out[os.path.relpath(p, root)] = p
    return out


def test_batched_driver_reproduces_sequential_artifacts(tmp_path):
    """run_batched_combination vs per-run run_combination with fresh
    per-run early stopping: identical early-stop rounds, identical per-run
    artifact trees (round JSON-lines byte-compatible, model.npz array-equal,
    training_tracking.pkl byte-equal), matching final metrics.

    num_rounds > chunk and inverted early stopping (AUC treated as a loss
    improves only on the first round) force mid-chunk stops, so this also
    pins the rewind-and-replay freeze semantics."""
    cfg = build_cfg(num_rounds=6, fused_schedule_chunk=4, global_patience=1)
    data = build_data(cfg)
    device_names = [f"dev-{i}" for i in range(N)]

    seq_root, bat_root = str(tmp_path / "seq"), str(tmp_path / "bat")
    writers = {
        root: ResultsWriter(root, cfg.network_size, cfg.experiment_name,
                            cfg.scen_name, cfg.metric, cfg.num_participants)
        for root in (seq_root, bat_root)
    }

    seq_outs = []
    for r in range(RUNS):
        early = GlobalEarlyStop(
            inverted=cfg.compat.inverted_global_early_stop,
            patience=cfg.global_patience)
        seq_outs.append(run_combination(
            cfg, data, N, "hybrid", "mse_avg", r, writer=writers[seq_root],
            early_stop=early, device_names=device_names,
            save_checkpoints=True))

    bat_outs = run_batched_combination(
        cfg, data, N, "hybrid", "mse_avg", writer=writers[bat_root],
        device_names=device_names, save_checkpoints=True)

    assert len(bat_outs) == RUNS
    for r in range(RUNS):
        # early-stop round parity: both modes ran the same number of rounds
        assert bat_outs[r]["rounds_run"] == seq_outs[r]["rounds_run"]
        assert bat_outs[r]["aggregation_count"] == \
            seq_outs[r]["aggregation_count"]
        np.testing.assert_allclose(bat_outs[r]["final_metrics"],
                                   seq_outs[r]["final_metrics"],
                                   rtol=1e-5, atol=1e-6)

    seq_files, bat_files = _walk_files(seq_root), _walk_files(bat_root)
    assert set(seq_files) == set(bat_files)  # identical artifact layout
    for rel in seq_files:
        if rel.endswith("_results.json") or rel.endswith(
                "verification_results.json"):
            with open(seq_files[rel], "rb") as a, open(bat_files[rel],
                                                       "rb") as b:
                assert a.read() == b.read(), f"{rel} not byte-compatible"
        elif rel.endswith("model.npz"):
            a, b = np.load(seq_files[rel]), np.load(bat_files[rel])
            assert set(a.files) == set(b.files)
            for k in a.files:
                np.testing.assert_allclose(a[k], b[k], rtol=1e-5, atol=1e-6)
        elif rel.endswith("training_tracking.pkl"):
            with open(seq_files[rel], "rb") as f:
                rows_a = pickle.load(f)
            with open(bat_files[rel], "rb") as f:
                rows_b = pickle.load(f)
            assert rows_a == rows_b


def test_batched_single_run_works():
    """R=1 is a valid batch (the bench sweeps R in {1, 3, 10})."""
    cfg = build_cfg(num_rounds=2)
    data = build_data(cfg)
    model = make_model("hybrid", DIM, shrink_lambda=cfg.shrink_lambda)
    bat = BatchedRunEngine(model, cfg, data, n_real=N, runs=1,
                           model_type="hybrid", update_type="mse_avg")
    outs, schedule, _ = bat.run_schedule_chunk(0, 2, np.ones(1, bool))
    res = bat.process_round(0, 1, schedule[1][0], outs, 1)
    assert res.aggregator in res.selected
    assert np.all(np.isfinite(res.client_metrics))


def test_batched_attack_matches_sequential_attacked_runs():
    """Attack x batched-runs composition: R=3 runs-axis-batched federations
    under a poisoning aggregator must reproduce 3 sequential attacked runs
    — same elections, same rejected-counter trajectories, same metric
    streams. The poison_fn's lax.cond schedule (start_round, every_k) must
    fire identically inside the vmapped scan."""
    from fedmse_tpu.federation.attack import AttackSpec, make_poison_fn

    cfg = build_cfg()
    data = build_data(cfg)
    model = make_model("hybrid", DIM, shrink_lambda=cfg.shrink_lambda)
    spec = AttackSpec(kind="scale", strength=50.0, start_round=1)

    seq = {}
    for r in range(RUNS):
        eng = RoundEngine(model, cfg, data, n_real=N,
                          rngs=ExperimentRngs(run=r), model_type="hybrid",
                          update_type="mse_avg", fused=True,
                          poison_fn=make_poison_fn(spec))
        seq[r] = eng.run_rounds(0, cfg.num_rounds)

    bat = BatchedRunEngine(model, cfg, data, n_real=N, runs=RUNS,
                           model_type="hybrid", update_type="mse_avg",
                           poison_fn=make_poison_fn(spec))
    outs, schedule, _ = bat.run_schedule_chunk(0, cfg.num_rounds,
                                               np.ones(RUNS, bool))
    attack_bit = False
    for i in range(cfg.num_rounds):
        for r in range(RUNS):
            res = bat.process_round(r, i, schedule[i][r], outs, i)
            ref = seq[r][i]
            assert res.selected == ref.selected
            assert res.aggregator == ref.aggregator
            assert [row["rejected_updates"]
                    for row in res.verification_results] == \
                   [row["rejected_updates"]
                    for row in ref.verification_results]
            np.testing.assert_allclose(res.client_metrics,
                                       ref.client_metrics,
                                       rtol=1e-5, atol=1e-6)
            attack_bit = attack_bit or any(
                row["rejected_updates"] > 0
                for row in res.verification_results)
    assert attack_bit  # the attack actually bit (rejections occurred)


def test_batched_time_metric_rejected():
    cfg = build_cfg(metric="time")
    data = build_data(cfg)
    model = make_model("hybrid", DIM, shrink_lambda=cfg.shrink_lambda)
    with pytest.raises(ValueError, match="time"):
        BatchedRunEngine(model, cfg, data, n_real=N, runs=2,
                         model_type="hybrid", update_type="mse_avg")
