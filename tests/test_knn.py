"""kNN scorer tests (fedmse_tpu/knn/): sklearn NearestNeighbors parity for
the exact blocked top-k (every bucket size, both model types, through a
checkpoint round-trip), the approximate-vs-exact recall bound, the
bf16-input/f32-accum contract of the distance tiles, bank lifecycle
(downsample / padding-invariance / persistence), and the score_kind
wiring through evaluator, serving engine, config and driver."""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fedmse_tpu.checkpointing import ResultsWriter, save_client_models
from fedmse_tpu.evaluation import make_evaluate_all
from fedmse_tpu.knn import (ReferenceBank, bank_path, build_banks,
                            downsample_latents, knn_kth_distance,
                            knn_smallest_k, load_bank, pow2_bank_size,
                            save_bank)
from fedmse_tpu.knn.score import dist_tiles
from fedmse_tpu.models import init_stacked_params, make_model
from fedmse_tpu.ops.distance import pairwise_sq_dists
from fedmse_tpu.serving import ServingEngine

pytestmark = pytest.mark.knn

DIM = 12
N = 3


def _data(seed=0, t=90):
    rng = np.random.default_rng(seed)
    test_x = rng.normal(size=(N, t, DIM)).astype(np.float32)
    test_m = (rng.random((N, t)) < 0.9).astype(np.float32)
    test_y = (rng.random((N, t)) < 0.4).astype(np.float32)
    train_xb = rng.normal(size=(N, 6, 10, DIM)).astype(np.float32)
    train_mb = np.ones((N, 6, 10), np.float32)
    return test_x, test_m, test_y, train_xb, train_mb


# ------------------------ exact top-k: sklearn parity ------------------------ #

@pytest.mark.parametrize("bank_size,k,count", [
    (128, 8, 128), (256, 5, 100), (512, 8, 512), (32, 8, 3), (64, 1, 64),
])
def test_exact_kth_distance_matches_sklearn(bank_size, k, count):
    """The blocked partial-top-k merge is EXACT: the kth distance equals
    sklearn NearestNeighbors on the same (valid) bank rows — including
    ragged banks (count < bank_size) and banks smaller than k."""
    from sklearn.neighbors import NearestNeighbors

    rng = np.random.default_rng(bank_size + k)
    bank = rng.normal(size=(bank_size, 7)).astype(np.float32)
    q = rng.normal(size=(41, 7)).astype(np.float32)
    got = np.asarray(knn_kth_distance(jnp.asarray(q), jnp.asarray(bank),
                                      count, k))
    kk = min(k, count)
    nn = NearestNeighbors(n_neighbors=kk).fit(bank[:count])
    want = nn.kneighbors(q)[0][:, kk - 1]
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_blocked_merge_equals_single_block():
    """Per-block partial top-k + merge == the unblocked top-k (the exactness
    argument: every true neighbor survives its own block's cut)."""
    rng = np.random.default_rng(1)
    bank = jnp.asarray(rng.normal(size=(1024, 7)).astype(np.float32))
    q = jnp.asarray(rng.normal(size=(17, 7)).astype(np.float32))
    a = np.asarray(knn_smallest_k(q, bank, 1024, 8, block=128))
    b = np.asarray(knn_smallest_k(q, bank, 1024, 8, block=1024))
    np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


# ---------------------- approx top-k: recall bound ---------------------- #

def test_approx_upper_bounds_exact_and_recall():
    """The approximate kth distance can never UNDERSHOOT the exact one
    (its candidate set is a subset of the bank), and with bins ≈ 32·k the
    per-bin partial reduce keeps expected recall ≈ 1 − (k−1)/(2·bins) —
    asserted with slack at ≥ 0.9 over the true neighbor sets."""
    from sklearn.neighbors import NearestNeighbors

    rng = np.random.default_rng(2)
    k = 8
    bank = rng.normal(size=(4096, 7)).astype(np.float32)
    q = rng.normal(size=(128, 7)).astype(np.float32)
    exact = np.asarray(knn_kth_distance(jnp.asarray(q), jnp.asarray(bank),
                                        4096, k))
    approx = np.asarray(knn_kth_distance(jnp.asarray(q), jnp.asarray(bank),
                                         4096, k, topk="approx"))
    assert np.all(approx >= exact - 1e-6)

    # recall: how many of the true k nearest the approx candidates kept —
    # reconstructed from the approx smallest-k distances (a true neighbor
    # was found iff its exact distance appears among the approx top-k)
    ap_sets = np.sqrt(np.asarray(knn_smallest_k(
        jnp.asarray(q), jnp.asarray(bank), 4096, k,
        topk="approx")))  # smallest-k returns SQUARED distances
    nn = NearestNeighbors(n_neighbors=k).fit(bank)
    true_d = nn.kneighbors(q)[0]
    hits = sum(np.isclose(ap_sets[i][:, None], true_d[i][None, :],
                          rtol=1e-5, atol=1e-6).any(axis=0).sum()
               for i in range(len(q)))
    recall = hits / (len(q) * k)
    # bins = pow2(32·8) = 256 -> expected ≈ 1 − 7/512 ≈ 0.986
    assert recall >= 0.9, recall


# ------------------- distance tiles: precision contract ------------------- #

def test_distance_tiles_bf16_inputs_f32_accumulation():
    """bf16 operands, f32 distances: the tile output dtype is float32 and
    matches f64 math on the bf16-ROUNDED inputs to f32-accumulation
    precision — a bf16 accumulator would be ~256x looser."""
    rng = np.random.default_rng(3)
    q64 = rng.normal(size=(64, 7))
    b64 = rng.normal(size=(256, 7))
    qb = jnp.asarray(q64, jnp.bfloat16)
    bb = jnp.asarray(b64, jnp.bfloat16)
    d = pairwise_sq_dists(qb, bb)
    assert d.dtype == jnp.float32
    # f64 reference on the SAME quantized operands: only accumulation
    # precision separates the two
    qr = np.asarray(qb, np.float64)
    br = np.asarray(bb, np.float64)
    want = ((qr ** 2).sum(1)[:, None] - 2 * qr @ br.T
            + (br ** 2).sum(1)[None, :])
    err = np.abs(np.asarray(d, np.float64) - want).max()
    assert err < 1e-4, err  # f32 accumulation; bf16 accum would be ~1e-1

    # f32 operands are bit-identical to the plain f32 formula
    qf, bf = jnp.asarray(q64, jnp.float32), jnp.asarray(b64, jnp.float32)
    np.testing.assert_array_equal(
        np.asarray(pairwise_sq_dists(qf, bf)),
        np.asarray(jnp.maximum(
            jnp.sum(qf * qf, axis=1)[:, None]
            - 2.0 * qf @ bf.T + jnp.sum(bf * bf, axis=1)[None, :], 0.0)))


def test_pallas_interpret_tile_matches_xla():
    """The Pallas distance-tile kernel (interpret mode on CPU) computes the
    identical tile math as the XLA path — same contract as
    ops/pallas_ae.py's kernel-vs-XLA pin."""
    rng = np.random.default_rng(4)
    q = jnp.asarray(rng.normal(size=(50, 7)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(256, 7)).astype(np.float32))
    dx = np.asarray(dist_tiles(q, b, mode="xla"))
    di = np.asarray(dist_tiles(q, b, mode="interpret"))
    np.testing.assert_allclose(dx, di, rtol=1e-5, atol=1e-5)
    with pytest.raises(ValueError, match="mode"):
        dist_tiles(q, b, mode="nope")


def test_centroid_density_unchanged_by_distance_hoist():
    """models/centroid.get_density now routes through ops/distance
    .norm_to_origin — bit-identical to the inlined formula it replaced."""
    from fedmse_tpu.models.centroid import fit_centroid

    rng = np.random.default_rng(5)
    lat = jnp.asarray(rng.normal(size=(100, 7)).astype(np.float32))
    cen = fit_centroid(lat)
    got = np.asarray(cen.get_density(lat))
    want = np.asarray(jnp.linalg.norm((lat - cen.mean) / cen.scale, axis=-1))
    np.testing.assert_array_equal(got, want)


# ------------------------------ bank lifecycle ------------------------------ #

def test_downsample_uniform_subset_and_caps():
    rng = np.random.default_rng(6)
    lat = jnp.asarray(rng.normal(size=(300, 7)).astype(np.float32))
    mask = jnp.asarray((np.arange(300) < 200).astype(np.float32))
    bank, count = downsample_latents(lat, mask, 128, jax.random.key(1))
    assert int(count) == 128 and bank.shape == (128, 7)
    # every bank row IS a valid latent row (a sample, not an aggregate);
    # float cancellation in the ‖q‖²−2qb+‖b‖² identity leaves ~1e-6
    # residue on exactly-coincident rows
    d = np.asarray(pairwise_sq_dists(bank, lat[:200]))
    assert (d.min(axis=1) < 1e-5).all()
    # capacity above the valid rows: keep all, zero the padding slots
    bank2, count2 = downsample_latents(lat, mask, 512, jax.random.key(1))
    assert int(count2) == 200 and bank2.shape == (512, 7)
    assert np.abs(np.asarray(bank2)[200:]).max() == 0.0
    # reproducible per key, different across keys
    bank3, _ = downsample_latents(lat, mask, 128, jax.random.key(1))
    np.testing.assert_array_equal(np.asarray(bank), np.asarray(bank3))
    bank4, _ = downsample_latents(lat, mask, 128, jax.random.key(2))
    assert not np.array_equal(np.asarray(bank), np.asarray(bank4))
    assert pow2_bank_size(100) == 128 and pow2_bank_size(128) == 128
    with pytest.raises(ValueError, match="bank_size"):
        pow2_bank_size(0)


def test_build_banks_padding_invariant_and_roundtrip(tmp_path):
    """Gateway i's bank depends only on its own rows + absolute index
    (fold_in keys): padding the client axis must not move it. Persistence
    round-trips exactly (npz beside the checkpoint tree)."""
    model = make_model("hybrid", DIM, shrink_lambda=1.0)
    params = init_stacked_params(model, jax.random.key(0), N + 2)
    _, _, _, train_xb, train_mb = _data()
    pad_xb = np.concatenate([train_xb, np.zeros_like(train_xb[:2])])
    pad_mb = np.concatenate([train_mb, np.zeros_like(train_mb[:2])])
    b1 = build_banks(model, jax.tree.map(lambda t: t[:N], params),
                     train_xb, train_mb, bank_size=32)
    b2 = build_banks(model, params, pad_xb, pad_mb, bank_size=32)
    np.testing.assert_array_equal(np.asarray(b1.latents),
                                  np.asarray(b2.latents)[:N])
    np.testing.assert_array_equal(np.asarray(b1.count),
                                  np.asarray(b2.count)[:N])
    # zero-mask pad gateways carry empty banks
    assert np.asarray(b2.count)[N:].tolist() == [0, 0]
    assert b1.num_gateways == N and b1.bank_size == 32 and b1.latent_dim == 7

    path = save_bank(os.path.join(str(tmp_path), "bank.npz"), b1)
    back = load_bank(path)
    np.testing.assert_array_equal(np.asarray(b1.latents),
                                  np.asarray(back.latents))
    np.testing.assert_array_equal(np.asarray(b1.count),
                                  np.asarray(back.count))


# ----------------- serving parity through checkpoint round-trip ----------------- #

@pytest.mark.parametrize("model_type", ["autoencoder", "hybrid"])
def test_served_knn_scores_match_evaluator_across_every_bucket(model_type,
                                                               tmp_path):
    """Acceptance pin (the serving contract, test_serving.py's twin for
    score_kind='knn'): served kNN scores for a CHECKPOINTED federation
    equal make_evaluate_all's scores-oracle to float32 tolerance at every
    bucket size, under BOTH model types — bank gather + bucket padding
    provably never perturb real rows."""
    model = make_model(model_type, DIM, shrink_lambda=1.0)
    params = init_stacked_params(model, jax.random.key(1), N)
    test_x, test_m, test_y, train_xb, train_mb = _data()
    oracle = np.asarray(make_evaluate_all(
        model, model_type, metric="scores", score_kind="knn",
        knn_bank_size=32, knn_k=4)(
            params, test_x, test_m, test_y, train_xb, train_mb))

    writer = ResultsWriter(str(tmp_path), N, "exp", "FL-IoT", "AUC", 0.5)
    names = [f"Client-{k}" for k in range(1, N + 1)]
    save_client_models(writer, 0, model_type, "mse_avg", names, params)
    eng = ServingEngine.from_checkpoint(
        writer, model, model_type, "mse_avg", names, run=0,
        train_x=train_xb, train_m=train_mb, max_bucket=16,
        score_kind="knn", knn_bank_size=32, knn_k=4)
    for g in range(N):
        for n_rows in (1, 2, 3, 4, 5, 7, 8, 9, 15, 16):
            got = eng.score(test_x[g, :n_rows], g)
            np.testing.assert_allclose(
                got, oracle[g, :n_rows], atol=1e-5,
                err_msg=f"{model_type} g={g} n={n_rows}")
    # oversize requests chunk at max_bucket and still agree
    got = eng.score(test_x[0, :37], 0)
    np.testing.assert_allclose(got, oracle[0, :37], atol=1e-5)
    assert sorted(eng.dispatches) == [1, 2, 4, 8, 16]


def test_serving_persisted_bank_path_and_validation(tmp_path):
    """A PERSISTED bank (save_bank -> load_bank -> banks=) serves the
    identical scores as the freshly built one — the deployment path where
    the serving process owns no training state; constructor validation
    rejects knn without banks and bad score kinds."""
    model = make_model("autoencoder", DIM)
    params = init_stacked_params(model, jax.random.key(2), N)
    test_x, _, _, train_xb, train_mb = _data()
    fresh = ServingEngine.from_federation(
        model, "autoencoder", params, train_x=train_xb, train_m=train_mb,
        score_kind="knn", knn_bank_size=32, max_bucket=16)
    writer = ResultsWriter(str(tmp_path), N, "exp", "FL-IoT", "AUC", 0.5)
    path = save_bank(bank_path(writer, 0, "autoencoder", "mse_avg"),
                     fresh.banks)
    reloaded = ServingEngine.from_federation(
        model, "autoencoder", params, banks=load_bank(path),
        score_kind="knn", max_bucket=16)
    for g in range(N):
        np.testing.assert_array_equal(fresh.score(test_x[g, :9], g),
                                      reloaded.score(test_x[g, :9], g))
    with pytest.raises(ValueError, match="banks"):
        ServingEngine(model, "autoencoder", params, score_kind="knn")
    with pytest.raises(ValueError, match="score_kind"):
        ServingEngine(model, "autoencoder", params, score_kind="nope")
    # a bank persisted from a DIFFERENT federation must fail loudly at
    # construction: inside jit the bank gathers clamp out-of-range
    # gateway indices silently (wrong scores, no exception)
    stale = ReferenceBank(latents=fresh.banks.latents[:N - 1],
                          count=fresh.banks.count[:N - 1])
    with pytest.raises(ValueError, match="different federation"):
        ServingEngine(model, "autoencoder", params, banks=stale,
                      score_kind="knn")
    # ... and a single-tenant engine must reject a multi-gateway bank
    # (its scorer takes banks[0] — a silent wrong-gateway score otherwise)
    single_params = jax.tree.map(lambda t: t[0], params)
    with pytest.raises(ValueError, match="single-tenant"):
        ServingEngine(model, "autoencoder", single_params,
                      banks=fresh.banks, score_kind="knn",
                      multi_tenant=False)


def test_knn_calibration_thresholds_kth_distance(tmp_path):
    """fit_calibration through a kNN engine calibrates per-gateway
    KTH-DISTANCE thresholds: the threshold is the requested percentile of
    the gateway's own kth-distance scores (the generic calibration path,
    no kNN special-casing)."""
    from fedmse_tpu.serving import fit_calibration

    model = make_model("hybrid", DIM, shrink_lambda=1.0)
    params = init_stacked_params(model, jax.random.key(3), N)
    _, _, _, train_xb, train_mb = _data()
    eng = ServingEngine.from_federation(
        model, "hybrid", params, train_x=train_xb, train_m=train_mb,
        score_kind="knn", knn_bank_size=32, max_bucket=16)
    rng = np.random.default_rng(7)
    valid_x = rng.normal(size=(N, 80, DIM)).astype(np.float32)
    cal = fit_calibration(eng, valid_x, percentile=90.0)
    assert cal.model_type == "hybrid"
    for g in range(N):
        scores = eng.score(valid_x[g], g)
        assert cal.thresholds[g] == pytest.approx(
            np.percentile(scores, 90.0), rel=1e-6)
        rate = float(np.mean(cal.verdicts(scores, g)))
        assert rate == pytest.approx(0.10, abs=0.03)


def test_approx_handles_ragged_banks():
    """Regression: a thin bank (count << capacity B) keeps its valid rows
    in the FIRST count slots; the binned partial reduce must stride its
    bins across the slot axis, or the valid prefix crams into a few bins
    and the kth candidate goes +inf (count < k·width) / recall silently
    degrades. With strided bins: count <= bins degenerates to EXACT, and
    every score stays finite whenever count > 0."""
    rng = np.random.default_rng(9)
    B, k, count = 4096, 8, 40
    bank = rng.normal(size=(B, 7)).astype(np.float32)
    q = rng.normal(size=(33, 7)).astype(np.float32)
    exact = np.asarray(knn_kth_distance(jnp.asarray(q), jnp.asarray(bank),
                                        count, k))
    approx = np.asarray(knn_kth_distance(jnp.asarray(q), jnp.asarray(bank),
                                         count, k, topk="approx"))
    assert np.isfinite(approx).all()
    # count (40) <= bins (256): every valid row is its own bin candidate,
    # so the approximation IS exact here
    np.testing.assert_allclose(approx, exact, rtol=1e-6, atol=1e-6)
    # a mid-size ragged bank (count > bins) stays a bounded approximation
    approx2 = np.asarray(knn_kth_distance(jnp.asarray(q), jnp.asarray(bank),
                                          512, k, topk="approx"))
    exact2 = np.asarray(knn_kth_distance(jnp.asarray(q), jnp.asarray(bank),
                                         512, k))
    assert np.isfinite(approx2).all() and np.all(approx2 >= exact2 - 1e-6)


def test_routed_onehot_path_matches_gather_fallback():
    """The serving engine's one-hot-matmul bank routing == the per-row
    gather fallback == the single-gateway scorer, for every row of a
    mixed-gateway batch (the extra one-hot contraction terms are exact
    zeros, so only f32 association separates the paths). Both exact and
    approx top-k, ragged counts included."""
    from fedmse_tpu.knn import routed_kth_distance

    rng = np.random.default_rng(8)
    n, b, l = 4, 64, 7
    bank = ReferenceBank(
        latents=jnp.asarray(rng.normal(size=(n, b, l)).astype(np.float32)),
        count=jnp.asarray([64, 10, 64, 3], jnp.int32))
    lat = jnp.asarray(rng.normal(size=(50, l)).astype(np.float32))
    gw = jnp.asarray(rng.integers(0, n, size=50).astype(np.int32))
    for topk in ("exact", "approx"):
        onehot = np.asarray(routed_kth_distance(lat, gw, bank, 8, topk=topk))
        gather = np.asarray(routed_kth_distance(lat, gw, bank, 8, topk=topk,
                                                max_onehot_cols=0))
        np.testing.assert_allclose(onehot, gather, rtol=1e-4, atol=1e-5)
        for g in range(n):
            sel = np.asarray(gw) == g
            single = np.asarray(knn_kth_distance(
                lat[sel], bank.latents[g], bank.count[g], 8, topk=topk))
            np.testing.assert_allclose(onehot[sel], single, rtol=1e-4,
                                       atol=1e-5)


# ----------------------------- evaluator wiring ----------------------------- #

def test_score_kind_auto_matches_reference_pairing():
    """score_kind='auto' must be EXACTLY the pre-knn behavior: AE-MSE under
    'autoencoder', centroid density under 'hybrid' (the default pairing
    every committed artifact was produced under)."""
    data = _data()
    test_x, test_m, test_y, train_xb, train_mb = data
    for model_type, kind in (("autoencoder", "mse"), ("hybrid", "centroid")):
        model = make_model(model_type, DIM, shrink_lambda=1.0)
        params = init_stacked_params(model, jax.random.key(4), N)
        auto = np.asarray(make_evaluate_all(model, model_type,
                                            metric="scores")(
            params, test_x, test_m, test_y, train_xb, train_mb))
        forced = np.asarray(make_evaluate_all(model, model_type,
                                              metric="scores",
                                              score_kind=kind)(
            params, test_x, test_m, test_y, train_xb, train_mb))
        np.testing.assert_array_equal(auto, forced)
    with pytest.raises(ValueError, match="score_kind"):
        make_evaluate_all(make_model("hybrid", DIM), "hybrid",
                          score_kind="nope")


def test_knn_beats_centroid_on_multimodal_latents():
    """The quality claim at test scale (ROADMAP 4): on multi-modal normal
    traffic with between-mode anomalies, the kNN score's AUC beats the
    single-prototype centroid's on every gateway (data/synthetic.py
    synthetic_multimodal_clients; the 500-client artifact is
    BENCH_KNN_r09)."""
    from fedmse_tpu.data import (build_dev_dataset, stack_clients,
                                 synthetic_multimodal_clients)

    clients = synthetic_multimodal_clients(n_clients=4, dim=DIM,
                                           n_normal=320, n_abnormal=64,
                                           modes=3, seed=0)
    dev_x = build_dev_dataset(clients, np.random.default_rng(0))
    data = stack_clients(clients, dev_x, 8)
    model = make_model("hybrid", DIM, shrink_lambda=1.0)
    params = init_stacked_params(model, jax.random.key(5), 4)
    args = (params, data.test_x, data.test_m, data.test_y,
            data.train_xb, data.train_mb)
    cen = np.asarray(make_evaluate_all(model, "hybrid")(*args))
    knn = np.asarray(make_evaluate_all(model, "hybrid", score_kind="knn",
                                       knn_bank_size=128)(*args))
    assert (knn >= cen).all(), (knn, cen)
    assert knn.mean() >= cen.mean() + 0.1


# ------------------------------ driver wiring ------------------------------ #

def test_cli_score_kind_knn_end_to_end(tmp_path):
    """--score-kind knn --knn-bank-size through the real CLI driver: the
    round metrics come from the kNN scorer, the serve smoke serves bank
    lookups, and the bank persists beside the calibration JSON."""
    from fedmse_tpu.config import DatasetConfig
    from fedmse_tpu.main import main as cli_main
    from tests.test_data import _write_client_csvs

    root = str(tmp_path / "shards")
    _write_client_csvs(root, 4, dim=6, n_normal=60, n_abnormal=24)
    cfg_path = os.path.join(root, "config.json")
    with open(cfg_path, "w") as f:
        json.dump(DatasetConfig.for_client_dirs(root, 4).to_json(), f)
    out = cli_main([
        "--dataset-config", cfg_path,
        "--model-types", "hybrid", "--update-types", "mse_avg",
        "--network-size", "4", "--dim-features", "6",
        "--epochs", "1", "--num-rounds", "1", "--batch-size", "8",
        "--checkpoint-dir", str(tmp_path / "ckpt"),
        "--experiment-name", "knn-t",
        "--score-kind", "knn", "--knn-bank-size", "16", "--knn-k", "3",
        "--serve", "--serve-rows", "128",
    ])
    smoke = out["serve_smoke"]
    assert smoke["score_kind"] == "knn"
    assert smoke["rows"] > 0
    assert os.path.exists(smoke["knn_bank_path"])
    bank = load_bank(smoke["knn_bank_path"])
    assert bank.num_gateways == 4 and bank.bank_size == 16
    assert os.path.exists(smoke["calibration_path"])
    json.dumps(smoke)  # report stays JSON-safe