"""Mixed-precision policy tests (ops/precision.py; ISSUE 5).

Four contracts, each a failure mode the policy must not have:
  * f32 IDENTITY — the default policy is the pre-policy code path: casts
    are no-ops (same buffers), the annotated reductions match the naive
    formulas bit-for-bit, and a policy-threaded engine's stacked data and
    params carry exactly the pre-PR dtypes. (The byte-level pin against
    history is the existing pipeline/chaos/batched-runs comparison suites,
    which all run under the default policy.)
  * bf16 QUALITY — quick-run AUC within 2e-3 of f32 on BOTH model types:
    bf16 is a compute format, not a different model.
  * ACCUMULATION — the score-deciding reductions (losses, aggregation
    einsum, Frobenius deltas, centroid stats) accumulate f32 under bf16
    operands, and bf16 aggregation merges exactly as f32 math would after
    rounding (the aggregation.py:35 regression).
  * NO f64 — neither the host data pipeline nor any jitted entry point
    traces a float64 value (the pre-PR loader kept f64 through prep).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fedmse_tpu.config import ExperimentConfig
from fedmse_tpu.data import build_dev_dataset, stack_clients, synthetic_clients
from fedmse_tpu.federation import RoundEngine
from fedmse_tpu.models import make_model, init_client_params, init_stacked_params
from fedmse_tpu.ops.precision import get_policy, tree_cast
from fedmse_tpu.utils.seeding import ExperimentRngs

pytestmark = pytest.mark.precision

DIM = 16
N_CLIENTS = 4


def _federation(precision: str):
    clients = synthetic_clients(n_clients=N_CLIENTS, dim=DIM, seed=0)
    dev = build_dev_dataset(clients, np.random.default_rng(1234))
    cfg = ExperimentConfig(network_size=N_CLIENTS, dim_features=DIM,
                           num_rounds=3, precision=precision)
    data = stack_clients(clients, dev, cfg.batch_size,
                         dtype=get_policy(precision).compute_dtype)
    return cfg, data


def _run(precision: str, model_type: str, update_type: str = "mse_avg"):
    cfg, data = _federation(precision)
    model = make_model(model_type, DIM, shrink_lambda=cfg.shrink_lambda,
                       precision=precision)
    engine = RoundEngine(model, cfg, data, n_real=N_CLIENTS,
                         rngs=ExperimentRngs(run=0, data_seed=cfg.data_seed),
                         model_type=model_type, update_type=update_type,
                         fused=True)
    results = engine.run_rounds(0, cfg.num_rounds)
    return results, engine


# --------------------------- policy object --------------------------- #

def test_policy_presets():
    f32 = get_policy("f32")
    bf16 = get_policy("bf16")
    assert f32.compute_dtype == jnp.float32
    # masters and accumulators are f32 under EVERY policy
    for p in (f32, bf16):
        assert p.param_dtype == jnp.float32
        assert p.accum_dtype == jnp.float32
    assert bf16.compute_dtype == jnp.bfloat16
    assert get_policy(bf16) is bf16  # pass-through
    with pytest.raises(ValueError, match="unknown precision"):
        get_policy("fp8")


def test_f32_cast_is_identity_same_buffers():
    tree = {"w": jnp.arange(6.0).reshape(2, 3),
            "n": jnp.arange(3),            # integer leaf: always untouched
            "b": jnp.ones(4, jnp.bfloat16)}
    out = get_policy("f32").cast_to_compute(tree)
    assert out["w"] is tree["w"]           # no copy, no new buffer
    assert out["n"] is tree["n"]
    assert out["b"].dtype == jnp.float32   # off-dtype inexact leaves DO cast
    back = tree_cast(out, jnp.bfloat16)
    assert back["n"] is out["n"]
    assert back["w"].dtype == jnp.bfloat16


# ------------------------- (b) f32 identity -------------------------- #

def test_f32_model_apply_matches_naive_matmul_chain():
    """The policy-threaded module (explicit Dense dtype/param_dtype) must be
    bit-identical to the raw f32 matmul chain — the pre-policy forward."""
    model = make_model("hybrid", DIM, shrink_lambda=5.0)  # default = f32
    params = init_client_params(model, jax.random.key(7))
    x = jnp.asarray(np.random.default_rng(0).normal(
        size=(33, DIM)).astype(np.float32))
    latent, recon = model.apply({"params": params}, x)

    def dense(p, v):
        return v @ p["kernel"] + p["bias"]
    enc, dec = params["encoder"], params["decoder"]
    z = dense(enc["Dense_1"], jax.nn.relu(dense(enc["Dense_0"], x)))
    r = dense(dec["Dense_1"], jax.nn.relu(dense(dec["Dense_0"], z)))
    assert latent.dtype == recon.dtype == jnp.float32
    np.testing.assert_array_equal(np.asarray(latent), np.asarray(z))
    np.testing.assert_array_equal(np.asarray(recon), np.asarray(r))


def test_f32_reductions_match_naive_formulas():
    """The explicit f32 accumulator annotations must be what XLA already did
    for f32 operands — bit-equal to the unannotated formulas."""
    from fedmse_tpu.ops.losses import masked_mean, mse_loss, per_sample_mse
    from fedmse_tpu.ops.stats import masked_mean_std

    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(40, 5)).astype(np.float32))
    y = jnp.asarray(rng.normal(size=(40, 5)).astype(np.float32))
    m = jnp.asarray((np.arange(40) < 29).astype(np.float32))
    np.testing.assert_array_equal(
        np.asarray(per_sample_mse(x, y)),
        np.asarray(jnp.mean(jnp.square(x - y), axis=-1)))
    # mse_loss is mean-of-row-means (the pre-PR structure), not one flat mean
    assert float(mse_loss(x, y)) == \
        float(jnp.mean(jnp.mean(jnp.square(x - y), axis=-1)))
    assert float(masked_mean(x[:, 0], None)) == float(jnp.mean(x[:, 0]))
    mean, std = masked_mean_std(x, m)
    naive_mean = jnp.sum(x * m[:, None], axis=0) / jnp.sum(m)
    np.testing.assert_array_equal(np.asarray(mean), np.asarray(naive_mean))
    assert mean.dtype == std.dtype == jnp.float32


def test_f32_run_dtypes_are_pre_pr():
    """Under the default policy every stacked tensor, param leaf and metric
    is float32 — exactly the pre-PR layout (the byte-level history pin is
    the pipeline/chaos/batched-runs comparison suites)."""
    results, engine = _run("f32", "hybrid")
    for leaf in jax.tree.leaves(engine.data):
        assert leaf.dtype == jnp.float32
    for leaf in jax.tree.leaves(engine.states.params):
        assert leaf.dtype == jnp.float32
    for r in results:
        assert r.client_metrics.dtype == np.float32


# ------------------------ (a) bf16 AUC parity ------------------------ #

@pytest.mark.parametrize("model_type", ["hybrid", "autoencoder"])
def test_bf16_quick_run_auc_parity(model_type):
    """bf16 policy: final AUC within 2e-3 of f32 on both model types —
    the ISSUE 5 quality pin (bf16 is quality-pinned, not bit-pinned)."""
    res32, eng32 = _run("f32", model_type)
    resbf, engbf = _run("bf16", model_type)
    auc32 = float(np.nanmean(res32[-1].client_metrics))
    aucbf = float(np.nanmean(resbf[-1].client_metrics))
    assert abs(auc32 - aucbf) < 2e-3, (auc32, aucbf)
    # masters stay f32, data and activations are bf16
    for leaf in jax.tree.leaves(engbf.states.params):
        assert leaf.dtype == jnp.float32
    assert engbf.data.train_xb.dtype == jnp.bfloat16
    assert engbf.data.test_x.dtype == jnp.bfloat16
    # masks/labels stay f32 bookkeeping
    assert engbf.data.train_mb.dtype == jnp.float32
    assert engbf.data.test_y.dtype == jnp.float32
    # metrics/scores come out f32 (accumulation surface)
    assert resbf[-1].client_metrics.dtype == np.float32


def test_bf16_adam_state_is_f32():
    _, engine = _run("bf16", "hybrid", update_type="fedprox")
    for leaf in jax.tree.leaves(engine.states.opt_state):
        if jnp.issubdtype(leaf.dtype, jnp.inexact):
            assert leaf.dtype == jnp.float32


# -------------------- accumulation-dtype contracts -------------------- #

def test_aggregation_bf16_merges_as_f32_math_after_rounding():
    """Regression for aggregation.py:35: the einsum must accumulate in f32
    (`preferred_element_type`), never in the leaf dtype. A bf16 merge must
    equal upcast-to-f32 -> weighted sum -> round-to-bf16 EXACTLY."""
    from fedmse_tpu.federation.aggregation import weighted_tree_mean

    rng = np.random.default_rng(11)
    tree = {"k": jnp.asarray(rng.normal(size=(6, 9, 4)).astype(np.float32)),
            "b": jnp.asarray(rng.normal(size=(6, 4)).astype(np.float32))}
    raw = jnp.asarray(rng.random(6).astype(np.float32))
    weights = raw / jnp.sum(raw)

    tree_bf = tree_cast(tree, jnp.bfloat16)
    got = weighted_tree_mean(tree_bf, weights)
    for key in tree:
        assert got[key].dtype == jnp.bfloat16  # leaf dtype preserved
        want = jnp.einsum("n,n...->...", weights,
                          tree_bf[key].astype(jnp.float32)
                          ).astype(jnp.bfloat16)
        np.testing.assert_array_equal(np.asarray(got[key], np.float32),
                                      np.asarray(want, np.float32))
    # and the f32 path is untouched by the annotation (bit-equal to naive)
    got32 = weighted_tree_mean(tree, weights)
    for key in tree:
        naive = jnp.einsum("n,n...->...", weights, tree[key])
        np.testing.assert_array_equal(np.asarray(got32[key]),
                                      np.asarray(naive))


def test_bf16_loss_and_score_reductions_accumulate_f32():
    from fedmse_tpu.ops.losses import (mse_loss, per_sample_mse, prox_term,
                                       shrink_loss)
    from fedmse_tpu.models.centroid import fit_centroid

    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.normal(size=(64, DIM)).astype(np.float32))
    xb = x.astype(jnp.bfloat16)
    y = (x + 0.1).astype(jnp.bfloat16)
    z = jnp.asarray(rng.normal(size=(64, 7)).astype(np.float32)
                    ).astype(jnp.bfloat16)
    assert per_sample_mse(xb, y).dtype == jnp.float32
    assert mse_loss(xb, y).dtype == jnp.float32
    assert shrink_loss(xb, y, z, 5.0).dtype == jnp.float32
    p = {"w": z}
    assert prox_term(p, jax.tree.map(jnp.zeros_like, p)).dtype == jnp.float32
    cen = fit_centroid(z)
    assert cen.mean.dtype == jnp.float32          # f32 master statistics
    assert cen.abs_threshold.dtype == jnp.float32
    assert cen.get_density(z).dtype == jnp.float32  # f32 score output
    # the f32-accumulated bf16 MSE tracks the true f32 value closely (a
    # bf16 accumulator over 16 features would already drift ~1e-2 here)
    true = float(jnp.mean(jnp.square(x - (x + 0.1))))
    assert float(mse_loss(xb, y)) == pytest.approx(true, rel=2e-2)


def test_bf16_verification_outputs_are_f32():
    """Frobenius deltas and perf scores — the Byzantine accept/reject
    inputs — come out f32 under the bf16 policy."""
    cfg, data = _federation("bf16")
    model = make_model("autoencoder", DIM, precision="bf16")
    engine = RoundEngine(model, cfg, data, n_real=N_CLIENTS,
                         rngs=ExperimentRngs(run=0, data_seed=cfg.data_seed),
                         model_type="autoencoder", update_type="avg",
                         fused=True)
    agg = jax.tree.map(lambda t: t[0], engine.states.params)
    onehot = jnp.zeros(data.num_clients_padded).at[0].set(1.0)
    outcome = engine.verify(engine.states, agg, engine._ver_x, engine._ver_m,
                            onehot, data.client_mask)
    assert outcome.param_delta.dtype == jnp.float32
    assert outcome.perf_change.dtype == jnp.float32


# ---------------- (c) pallas bf16 kernel / XLA parity ---------------- #

@pytest.mark.parametrize("rows", [1, 2, 16, 100, 512, 513, 1024])
def test_pallas_bf16_matches_xla_at_every_bucket(rows):
    """The bf16 kernel (interpret mode on CPU — same kernel program) and
    the bf16 XLA fallback run the same cast/accumulate schedule, so they
    must agree to f32-accumulation tolerance at every row bucket."""
    from fedmse_tpu.ops.pallas_ae import fused_forward_stats

    model = make_model("hybrid", 115, shrink_lambda=5.0)
    params = init_client_params(model, jax.random.key(3))
    x = jnp.asarray(np.random.default_rng(0).normal(
        size=(rows, 115)).astype(np.float32))
    out_k = fused_forward_stats(params, x, mode="interpret",
                                compute_dtype=jnp.bfloat16, block_rows=512)
    out_x = fused_forward_stats(params, x, mode="xla",
                                compute_dtype=jnp.bfloat16, block_rows=512)
    for a, b in zip(out_k, out_x):
        assert a.dtype == jnp.float32  # packed outputs are f32 scores
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    # and against the bf16 flax forward: same matmuls at bf16 resolution
    mbf = make_model("hybrid", 115, shrink_lambda=5.0, precision="bf16")
    lat_ref, recon_ref = mbf.apply({"params": params}, x)
    np.testing.assert_allclose(np.asarray(out_k[0]),
                               np.asarray(lat_ref, np.float32), atol=0.05)


# ------------------------- serving precision ------------------------- #

@pytest.mark.parametrize("model_type", ["autoencoder", "hybrid"])
def test_serving_bf16_scores_match_f32_at_every_bucket(model_type):
    """bf16 serving: f32 score outputs within bf16 resolution of the f32
    engine at every compiled bucket — calibration thresholds stay
    comparable across policies."""
    from fedmse_tpu.serving.engine import ServingEngine, fit_gateway_centroids

    rng = np.random.default_rng(2)
    model = make_model(model_type, DIM, shrink_lambda=5.0)
    params = init_stacked_params(model, jax.random.key(0), 3)
    train_x = rng.normal(size=(3, 64, DIM)).astype(np.float32)
    cen = (fit_gateway_centroids(model, params, train_x)
           if model_type == "hybrid" else None)
    e32 = ServingEngine(model, model_type, params, cen, max_bucket=16)
    ebf = ServingEngine(model, model_type, params, cen, max_bucket=16,
                        precision="bf16")
    assert all(l.dtype == jnp.bfloat16 for l in jax.tree.leaves(ebf.params))
    for b in e32.buckets:
        rows = rng.normal(size=(b, DIM)).astype(np.float32)
        gws = rng.integers(0, 3, size=b).astype(np.int32)
        s32 = e32.score(rows, gws)
        sbf = ebf.score(rows, gws)
        assert s32.dtype == sbf.dtype == np.float32
        np.testing.assert_allclose(sbf, s32, rtol=0.05, atol=1e-3)


# --------------------------- (d) no-f64 guard --------------------------- #

def test_host_pipeline_and_stacked_arrays_never_f64(tmp_path):
    """The loader satellite: CSV shards cast to f32 at the load boundary,
    the scaler preserves f32 through prep, and no stacked device tensor is
    float64 — host RAM and H2D bytes halve even on the f32 policy."""
    import pandas as pd
    from fedmse_tpu.config import DatasetConfig
    from fedmse_tpu.data import load_data, prepare_clients

    rng = np.random.default_rng(0)
    for split, n in (("normal", 80), ("abnormal", 20), ("test_normal", 10)):
        d = tmp_path / "Client-1" / split
        d.mkdir(parents=True)
        pd.DataFrame(rng.normal(size=(n, 6))).to_csv(
            d / "data.csv", index=False, header=False)

    df = load_data(str(tmp_path / "Client-1" / "normal"))
    assert all(dt == np.float32 for dt in df.dtypes), df.dtypes
    # the raw f64 parse stays available for the shard-prep rewrite path
    df64 = load_data(str(tmp_path / "Client-1" / "normal"), dtype=None)
    assert all(dt == np.float64 for dt in df64.dtypes)
    np.testing.assert_array_equal(df.values,
                                  df64.values.astype(np.float32))

    ds = DatasetConfig.for_client_dirs(str(tmp_path), 1)
    cfg = ExperimentConfig(dim_features=6, network_size=1)
    clients = prepare_clients(ds, cfg, np.random.default_rng(1))
    c = clients[0]
    for name in ("train_x", "valid_x", "test_x", "test_y"):
        assert getattr(c, name).dtype == np.float32, name
    assert all(dt == np.float32 for dt in c.dev_raw.dtypes)
    assert c.scaler.mean_.dtype == np.float32

    dev = build_dev_dataset(clients, np.random.default_rng(2))
    data = stack_clients(clients, dev, cfg.batch_size)
    for leaf in jax.tree.leaves(data):
        assert leaf.dtype != jnp.float64


@pytest.mark.parametrize("precision", ["f32", "bf16"])
def test_no_f64_tracers_in_jitted_entry_points(precision):
    """Trace every jitted engine entry point (train / scores / aggregate /
    verify / evaluate and the fused round body) and assert no float64 aval
    appears anywhere in the jaxpr — the device-side half of the f64-leak
    guard (avals print as f64[...], so a string scan over the jaxpr covers
    eqn intermediates, subjaxprs and literals in one pass)."""
    cfg, data = _federation(precision)
    model = make_model("hybrid", DIM, shrink_lambda=cfg.shrink_lambda,
                       precision=precision)
    engine = RoundEngine(model, cfg, data, n_real=N_CLIENTS,
                         rngs=ExperimentRngs(run=0, data_seed=cfg.data_seed),
                         model_type="hybrid", update_type="mse_avg",
                         fused=True)
    engine._build_fused()
    n_pad = data.num_clients_padded
    sel = [0, 2]
    sel_idx, sel_mask = engine._selection_arrays(sel)
    key = jax.random.key(0)

    entry_points = {
        "round_body": lambda: jax.make_jaxpr(engine._fused_round)(
            engine.states, data, engine._ver_x, engine._ver_m,
            jnp.asarray(sel_idx), jnp.asarray(sel_mask),
            engine._agg_count_padded(), key, jnp.int32(0)),
        "train_all": lambda: jax.make_jaxpr(
            lambda s, o: engine.train_all(
                s, o, s, jnp.asarray(sel_mask), data.train_xb, data.train_mb,
                data.valid_xb, data.valid_mb))(
                    engine.states.params, engine.states.opt_state),
        "scores": lambda: jax.make_jaxpr(engine.scores_fn)(
            engine.states.params, data.valid_x[0], data.valid_m[0], key),
        "aggregate": lambda: jax.make_jaxpr(
            lambda p: engine.aggregate(p, jnp.asarray(sel_mask), data.dev_x))(
                engine.states.params),
        "evaluate": lambda: jax.make_jaxpr(engine.evaluate_all)(
            engine.states.params, data.test_x, data.test_m, data.test_y,
            data.train_xb, data.train_mb),
        "verify": lambda: jax.make_jaxpr(
            lambda s, a: engine.verify(
                s, a, engine._ver_x, engine._ver_m,
                jnp.zeros(n_pad).at[0].set(1.0), data.client_mask))(
                    engine.states,
                    jax.tree.map(lambda t: t[0], engine.states.params)),
    }
    for name, trace in entry_points.items():
        jaxpr = str(trace())
        assert "f64[" not in jaxpr, f"float64 tracer in {name}"
