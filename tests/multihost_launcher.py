"""Hardened launcher for the 2-process multi-controller test workers.

PR 11 documented 3 two-process in-suite ERRORS that pass standalone: the
worker pair (tests/multihost_worker.py) is spawned mid-suite on a loaded
1-core box, and the spawn seam is environment-fragile in two ways the
old inline fixture could not absorb:

  * the free coordinator port is found by bind-then-close, so another
    process (or a previous worker's lingering socket in TIME_WAIT) can
    steal it before `jax.distributed.initialize` binds — the pair then
    dies on a coordinator connect error that no rerun of the test body
    can fix, because the fixture never re-picked a port;
  * under suite memory/CPU pressure the two interpreter+jax cold starts
    (~20 s each standalone) can blow the fixed communicate() timeout.

This module is the one home of the spawn protocol: fresh port PER
ATTEMPT, scrubbed environment, and a bounded retry that relaunches the
whole pair. A deterministic assertion failure inside a worker still
fails — it reproduces on the retry and the final attempt's output is
raised — so the retry only absorbs spawn-level environment flakes.
Every multi-process fixture (tests/conftest.py two_process_outputs, the
pod-scale checks in tests/test_podscale.py) goes through here, so
tier-1 holds its 0-error bar in one in-suite run.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
from typing import Dict, List, Optional, Sequence

# env that must not leak from the parent suite into workers: the workers
# pick their own platform/device topology, and a pallas pool would make
# jax probe remote devices during the coordinator handshake
_SCRUB = ("PALLAS_AXON_POOL_IPS", "XLA_FLAGS", "JAX_PLATFORMS")


def free_port() -> int:
    """A currently-free localhost port (best effort: freed on return)."""
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def worker_env(extra: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    env = {k: v for k, v in os.environ.items() if k not in _SCRUB}
    if extra:
        env.update(extra)
    return env


def launch_worker_pair(script: str, args: Sequence[str] = (),
                       n_processes: int = 2, timeout: int = 420,
                       attempts: int = 2,
                       extra_env: Optional[Dict[str, str]] = None
                       ) -> List[str]:
    """Run `script` once per process id against one fresh coordinator port
    (worker argv: `script <port> <pid> *args`), returning each process's
    combined stdout+stderr. On timeout or nonzero exit the WHOLE pair is
    relaunched on a new port, up to `attempts` times; the final failure
    raises with the last outputs attached."""
    last = "no attempt ran"
    for attempt in range(attempts):
        port = free_port()
        procs = [subprocess.Popen(
            [sys.executable, script, str(port), str(pid),
             *map(str, args)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=worker_env(extra_env)) for pid in range(n_processes)]
        outs: List[str] = []
        failed = False
        for p in procs:
            try:
                out, _ = p.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                for q in procs:
                    try:
                        q.communicate(timeout=10)
                    except Exception:
                        pass
                failed = True
                last = (f"worker pair timed out after {timeout}s "
                        f"(attempt {attempt + 1}/{attempts})")
                outs = []
                break
            outs.append(out)
            if p.returncode != 0:
                failed = True
        if not failed:
            return outs
        if outs:
            last = "\n--- worker ---\n".join(o[-2000:] for o in outs)
    raise RuntimeError(
        f"multihost worker pair failed after {attempts} attempts:\n{last}")


def match_all(outs: Sequence[str], ok_pattern: str):
    """re.search `ok_pattern` in every worker output; assert all matched and
    return the match objects (shared by every two-process assertion)."""
    import re
    results = [re.search(ok_pattern, o) for o in outs]
    assert all(results), [o[-500:] for o in outs]
    return results
