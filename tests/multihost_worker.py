"""Worker process for the 2-process multi-controller (DCN-path) validation.

Launched twice by tests/test_parallel.py::test_two_process_federation with
process ids 0 and 1. Each process joins a localhost coordinator via
`fedmse_tpu.parallel.initialize_multihost` (the same entry `fedmse_tpu.main`
calls on pod hosts), contributes 4 virtual CPU devices to an 8-device global
`clients` mesh, and runs ONE full federated round over the pod-spanning mesh
— local training, election, aggregation all-reduce (the DCN collective),
verification, evaluation — asserting identical, finite results on both
processes. This exercises exactly the multi-process code paths that degrade
to no-ops on one host: `jax.distributed.initialize`,
`make_array_from_process_local_data` placement (parallel/mesh.py:_place) and
`host_fetch`'s `process_allgather` reassembly.

Usage: multihost_worker.py <coordinator_port> <process_id> [mode]

mode 'round' (default): one federated round over the pod mesh.
mode 'midstop': the fused-schedule chunk path with an early stop firing
MID-chunk — the rewind+replay must produce the per-round path's exact
state on BOTH processes (the decision is broadcast from process 0,
parallel/multihost.py::uniform_decision), validating that the fused
schedule is safe as the multi-controller default.
mode 'both': 'round' then 'midstop' then 'podtier' in one process — the
test suite uses this so every two-process validation pays the
worker-pair spawn (jax import + distributed init, ~20 s/process on this
1-core box) only once.
mode 'podtier': the host-sharded tiered federation (DESIGN.md §20) over
the real 2-process runtime — each process tiers only its 6 of 12
clients, rounds run over the cross-host cohort assembly, and the pod
writes a host-sharded checkpoint. With PODSCALE_OUTDIR set, results and
the checkpoint land there for the parent's cross-process / vs-single-
process assertions (tests/test_podscale.py).
"""

import os
import sys

if __name__ == "__main__":
    # worker-process only: the parent suite imports this module for the
    # shared podtier scenario (tests/test_podscale.py) and must keep its
    # own 8-device flags
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from fedmse_tpu.utils.platform import force_cpu_platform  # noqa: E402

force_cpu_platform()  # no device count: backends must not init before
# jax.distributed.initialize below

import jax  # noqa: E402


class _StopAtCall:
    """Deterministic early-stop stub: fires on the n-th should_stop call
    (call counts are identical on every process, so the rigged decision is
    uniform before the broadcast even runs)."""

    def __init__(self, n: int):
        self.n = n
        self.calls = 0

    def should_stop(self, metrics) -> bool:
        self.calls += 1
        return self.calls >= self.n


def run_midstop(pid: int) -> None:
    import numpy as np

    from fedmse_tpu.config import CompatConfig, ExperimentConfig
    from fedmse_tpu.data import (build_dev_dataset, stack_clients,
                                 synthetic_clients)
    from fedmse_tpu.main import run_combination
    from fedmse_tpu.parallel import client_mesh

    dim, n_real = 8, 8
    base = ExperimentConfig(dim_features=dim, network_size=n_real, epochs=1,
                            num_rounds=4, batch_size=4,
                            fused_schedule_chunk=4,
                            compat=CompatConfig(vote_tie_break=False))
    rng_clients = synthetic_clients(n_clients=n_real, dim=dim, n_normal=40,
                                    n_abnormal=16)
    from fedmse_tpu.utils.seeding import ExperimentRngs
    dev_x = build_dev_dataset(rng_clients, ExperimentRngs(run=0).data_rng)
    data = stack_clients(rng_clients, dev_x, base.batch_size, pad_clients_to=8)
    mesh = client_mesh()
    assert mesh.devices.size == 8

    # stop fires on the 2nd bookkeep call -> mid-chunk of the 4-round chunk
    sched = run_combination(base.replace(fused_schedule=True), data, n_real,
                            "hybrid", "mse_avg", run=0,
                            early_stop=_StopAtCall(2), mesh=mesh)
    per_round = run_combination(base.replace(fused_schedule=False), data,
                                n_real, "hybrid", "mse_avg", run=0,
                                early_stop=_StopAtCall(2), mesh=mesh)
    assert sched["rounds_run"] == per_round["rounds_run"] == 2, (
        sched["rounds_run"], per_round["rounds_run"])
    # tight atol on purpose: a MID-chunk stop rewinds to the chunk-entry
    # snapshot and replays the prefix through run_round_fused — the very
    # same per-round program the fused_schedule=False path runs, with the
    # same selections/keys — so the final states must agree bit-for-bit,
    # not merely to the scan-vs-per-round rtol=1e-4 (test_driver.py:137).
    np.testing.assert_allclose(sched["final_metrics"],
                               per_round["final_metrics"], atol=1e-6)
    print(f"MIDSTOP_OK pid={pid} rounds={sched['rounds_run']} "
          f"mean={float(np.nanmean(sched['final_metrics'])):.6f}", flush=True)


def main() -> None:
    port, pid = sys.argv[1], int(sys.argv[2])
    mode = sys.argv[3] if len(sys.argv) > 3 else "round"
    if mode not in ("round", "midstop", "podtier", "both"):
        sys.exit(f"unknown mode {mode!r}")  # a typo must fail loudly,
        # not silently run 'round'

    from fedmse_tpu.parallel import initialize_multihost
    initialize_multihost(coordinator_address=f"localhost:{port}",
                         num_processes=2, process_id=pid)
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 8 and len(jax.local_devices()) == 4

    if mode == "midstop":
        run_midstop(pid)
        return
    if mode == "podtier":
        run_podtier(pid)
        return

    run_round(pid)
    if mode == "both":
        run_midstop(pid)
        run_podtier(pid)


def run_round(pid: int) -> None:
    import numpy as np

    from fedmse_tpu.config import ExperimentConfig
    from fedmse_tpu.data import (build_dev_dataset, stack_clients,
                                 synthetic_clients)
    from fedmse_tpu.federation import RoundEngine
    from fedmse_tpu.models import make_model
    from fedmse_tpu.parallel import client_mesh, shard_federation
    from fedmse_tpu.utils.seeding import ExperimentRngs

    dim, n_real = 8, 8
    cfg = ExperimentConfig(dim_features=dim, network_size=n_real, epochs=1,
                           num_rounds=1, batch_size=4)
    rngs = ExperimentRngs(run=0)
    # deterministic in the PRNG keys => every process builds identical
    # host-side state before placement (parallel/multihost.py docstring)
    clients = synthetic_clients(n_clients=n_real, dim=dim, n_normal=40,
                                n_abnormal=16)
    dev_x = build_dev_dataset(clients, rngs.data_rng)
    data = stack_clients(clients, dev_x, cfg.batch_size, pad_clients_to=8)

    mesh = client_mesh()  # all 8 global devices: spans both processes
    assert mesh.devices.size == 8
    model = make_model("hybrid", dim, shrink_lambda=cfg.shrink_lambda)
    engine = RoundEngine(model, cfg, data, n_real=n_real, rngs=rngs,
                         model_type="hybrid", update_type="mse_avg",
                         fused=True)
    engine.data, engine.states = shard_federation(data, engine.states, mesh)
    engine._ver_x, engine._ver_m = engine._verification_tensors()

    result = engine.run_round(0)
    metrics = np.asarray(result.client_metrics)
    assert metrics.shape == (n_real,), metrics.shape
    assert np.all(np.isfinite(metrics)), metrics
    assert result.aggregator is not None
    # the host control plane must agree across processes (same seeds, same
    # allgathered device results) — print for the parent to cross-check
    print(f"MULTIHOST_OK pid={pid} agg={result.aggregator} "
          f"mean={float(np.nanmean(metrics)):.6f}", flush=True)

    run_hostlocal(pid, cfg, clients, dev_x, mesh, n_real, result)


def run_hostlocal(pid: int, cfg, clients, dev_x, mesh, n_real: int,
                  replicated_result) -> None:
    """The shard-native data path under a REAL 2-process runtime: each
    process stacks ONLY the client rows its devices own (half the host
    bytes), donates them via `make_array_from_process_local_data` local
    slices, and the federated round must reproduce the fully-replicated
    placement bit-for-bit. Also pins the hierarchical int8 merge across the
    REAL process boundary (num_groups=0 -> one group per process, so the
    quantized payload crosses the actual DCN/gloo link)."""
    import numpy as np
    import jax

    from fedmse_tpu.data.stacking import stack_clients, stack_dims
    from fedmse_tpu.federation import RoundEngine
    from fedmse_tpu.models import make_model
    from fedmse_tpu.parallel import (make_hierarchical_aggregate,
                                     make_shardmap_aggregate,
                                     process_client_rows, shard_federation)
    from fedmse_tpu.utils.seeding import ExperimentRngs

    n_pad = 8
    dims = stack_dims(clients, cfg.batch_size, pad_clients_to=n_pad)
    start, stop = process_client_rows(n_pad, mesh)
    local = stack_clients(clients, dev_x, cfg.batch_size,
                          client_range=(start, stop), dims=dims)
    full_rows = n_pad
    local_rows = stop - start
    assert local_rows * jax.process_count() == full_rows, (start, stop)
    local_bytes = sum(l.nbytes for l in jax.tree.leaves(local))
    gdata, _ = shard_federation(local, None, mesh, host_local=True,
                                global_clients=n_pad)
    assert gdata.num_clients_padded == n_pad

    model = make_model("hybrid", cfg.dim_features,
                       shrink_lambda=cfg.shrink_lambda)
    engine = RoundEngine(model, cfg, gdata, n_real=n_real,
                         rngs=ExperimentRngs(run=0), model_type="hybrid",
                         update_type="mse_avg", fused=True, mesh=mesh)
    result = engine.run_round(0)
    # host-local placement must be invisible to the math: identical global
    # arrays -> identical program -> identical round
    assert result.aggregator == replicated_result.aggregator
    np.testing.assert_array_equal(result.client_metrics,
                                  replicated_result.client_metrics)

    # hierarchical quantized merge across the REAL host boundary: intra-
    # process psum exact, int8 payloads over the gloo link, vs exact f32
    exact = make_shardmap_aggregate(model, "avg", mesh)
    quant = make_hierarchical_aggregate(model, "avg", mesh, num_groups=0)
    sel = gdata.client_mask
    agg_e, w_e = exact(engine.states.params, sel, gdata.dev_x)
    agg_q, w_q = quant(engine.states.params, sel, gdata.dev_x)
    from fedmse_tpu.parallel.mesh import host_fetch
    w_err = np.abs(np.asarray(host_fetch(w_e))
                   - np.asarray(host_fetch(w_q))).max()
    assert w_err == 0.0, w_err  # weights are never quantized
    max_err = max(
        float(np.abs(np.asarray(a) - np.asarray(b)).max())
        for a, b in zip(jax.tree.leaves(host_fetch(agg_e)),
                        jax.tree.leaves(host_fetch(agg_q))))
    scale = max(float(np.abs(np.asarray(a)).max())
                for a in jax.tree.leaves(host_fetch(agg_e)))
    # 2 hosts x blockmax/254 per element; blockmax <= global leaf max
    assert max_err <= 2 * scale / 254 + 1e-7, (max_err, scale)
    print(f"MULTIHOST_LOCAL_OK pid={pid} local_rows={local_rows} "
          f"global_rows={full_rows} local_bytes={local_bytes} "
          f"quant_err={max_err:.2e}", flush=True)

    # K-cluster int8 merge across the SAME real process boundary
    # (DESIGN.md §23): per-device [K, ...] partial sheets, intra-process
    # psum exact, int8 cluster-row payloads over the gloo link — pinned
    # against the exact clustered shard_map twin
    from fedmse_tpu.parallel import (make_clustered_hierarchical_aggregate,
                                     make_clustered_shardmap_aggregate,
                                     seam)
    import jax.numpy as jnp

    from fedmse_tpu.parallel.mesh import shard_clients
    k = 2
    cluster = shard_clients(jnp.arange(n_pad, dtype=jnp.int32) % k, mesh)
    cexact = make_clustered_shardmap_aggregate(model, "avg", mesh, k)
    cquant = make_clustered_hierarchical_aggregate(model, "avg", mesh, k,
                                                   num_groups=0)
    ce, we, he = cexact(engine.states.params, sel, gdata.dev_x, cluster)
    cq, wq, hq = cquant(engine.states.params, sel, gdata.dev_x, cluster)
    cw_err = np.abs(np.asarray(host_fetch(we))
                    - np.asarray(host_fetch(wq))).max()
    assert cw_err == 0.0, cw_err  # row sums / weights never quantized
    np.testing.assert_array_equal(np.asarray(host_fetch(he)),
                                  np.asarray(host_fetch(hq)))
    ck_err = max(
        float(np.abs(np.asarray(a) - np.asarray(b)).max())
        for a, b in zip(jax.tree.leaves(host_fetch(ce)),
                        jax.tree.leaves(host_fetch(cq))))
    ck_scale = max(float(np.abs(np.asarray(a)).max())
                   for a in jax.tree.leaves(host_fetch(ce)))
    assert ck_err <= 2 * ck_scale / 254 + 1e-7, (ck_err, ck_scale)
    prof = seam.snapshot()["merge_profiles"]["quantized"]
    assert prof["k"] == k and prof["n_groups"] == 2, prof
    print(f"MULTIHOST_CLUSTER_OK pid={pid} k={k} "
          f"dcn_bytes={int(prof['dcn_bytes'])} "
          f"cluster_err={ck_err:.2e}", flush=True)


def podtier_config():
    """The pod-tier scenario, shared with the parent's single-process
    reference run (tests/test_podscale.py): 12 clients, 2 hosts tiering
    6 each, full participation so the H=1 and H=2 cohorts cover the
    same fleet (the vs-single-process AUC bar compares like with
    like)."""
    from fedmse_tpu.config import CompatConfig, ExperimentConfig

    dim, n_real = 8, 12
    # shared_last_client_val (the reference quirk) needs the LAST client's
    # validation rows on every host — unsupported (by design) when each
    # host tiers only its own shard, so the pod scenario verifies on each
    # client's own val rows
    cfg = ExperimentConfig(dim_features=dim, hidden_neus=6, latent_dim=3,
                           network_size=n_real, epochs=2, num_rounds=3,
                           batch_size=4, num_participants=1.0,
                           state_layout="tiered",
                           compat=CompatConfig(shared_last_client_val=False))
    return cfg, dim, n_real


def podtier_federation(cfg, dim: int, n_real: int):
    from fedmse_tpu.data import (build_dev_dataset, stack_clients,
                                 synthetic_clients)
    from fedmse_tpu.utils.seeding import ExperimentRngs

    clients = synthetic_clients(n_clients=n_real, dim=dim, n_normal=40,
                                n_abnormal=16)
    dev_x = build_dev_dataset(clients, ExperimentRngs(run=0).data_rng)
    return stack_clients(clients, dev_x, cfg.batch_size)


def run_podtier(pid: int) -> None:
    """Host-sharded tiered federation (DESIGN.md §20) across the REAL
    2-process runtime: stratified cohort selection, host-local tier
    gathers assembled into the pod-global cohort slab, the lane-block
    scatter back into each process's shard, and the pod-sharded
    checkpoint (save_shard + barrier) every round."""
    import numpy as np

    from fedmse_tpu.checkpointing.io import CheckpointManager
    from fedmse_tpu.federation.tiered import run_tiered_combination
    from fedmse_tpu.parallel import client_mesh

    cfg, dim, n_real = podtier_config()
    data = podtier_federation(cfg, dim, n_real)
    mesh = client_mesh()
    outdir = os.environ.get("PODSCALE_OUTDIR")
    resume = (CheckpointManager(os.path.join(outdir, "podckpt"))
              if outdir else None)
    out = run_tiered_combination(cfg, data, n_real, "hybrid", "mse_avg", 0,
                                 mesh=mesh, resume=resume)
    fm = np.asarray(out["final_metrics"])
    assert fm.shape == (n_real,), fm.shape
    assert np.all(np.isfinite(fm)), fm
    if outdir:
        np.savez(os.path.join(outdir, f"pod_result_{pid}.npz"),
                 final_metrics=fm,
                 best_final=np.float64(out["best_final"]),
                 aggregation_count=np.asarray(out["aggregation_count"]))
    # both processes must print the identical digest (allgathered
    # outputs + shared host streams -> identical control plane)
    print(f"PODTIER_OK pid={pid} best={out['best_final']:.6f} "
          f"mean={float(np.nanmean(fm)):.6f} "
          f"agg={out['aggregation_count']}", flush=True)


if __name__ == "__main__":
    main()
