"""Worker process for the 2-process multi-controller (DCN-path) validation.

Launched twice by tests/test_parallel.py::test_two_process_federation with
process ids 0 and 1. Each process joins a localhost coordinator via
`fedmse_tpu.parallel.initialize_multihost` (the same entry `fedmse_tpu.main`
calls on pod hosts), contributes 4 virtual CPU devices to an 8-device global
`clients` mesh, and runs ONE full federated round over the pod-spanning mesh
— local training, election, aggregation all-reduce (the DCN collective),
verification, evaluation — asserting identical, finite results on both
processes. This exercises exactly the multi-process code paths that degrade
to no-ops on one host: `jax.distributed.initialize`,
`make_array_from_process_local_data` placement (parallel/mesh.py:_place) and
`host_fetch`'s `process_allgather` reassembly.

Usage: multihost_worker.py <coordinator_port> <process_id>
"""

import os
import sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from fedmse_tpu.utils.platform import force_cpu_platform  # noqa: E402

force_cpu_platform()  # no device count: backends must not init before
# jax.distributed.initialize below

import jax  # noqa: E402


def main() -> None:
    port, pid = sys.argv[1], int(sys.argv[2])

    from fedmse_tpu.parallel import initialize_multihost
    initialize_multihost(coordinator_address=f"localhost:{port}",
                         num_processes=2, process_id=pid)
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 8 and len(jax.local_devices()) == 4

    import numpy as np

    from fedmse_tpu.config import ExperimentConfig
    from fedmse_tpu.data import (build_dev_dataset, stack_clients,
                                 synthetic_clients)
    from fedmse_tpu.federation import RoundEngine
    from fedmse_tpu.models import make_model
    from fedmse_tpu.parallel import client_mesh, shard_federation
    from fedmse_tpu.utils.seeding import ExperimentRngs

    dim, n_real = 8, 8
    cfg = ExperimentConfig(dim_features=dim, network_size=n_real, epochs=1,
                           num_rounds=1, batch_size=4)
    rngs = ExperimentRngs(run=0)
    # deterministic in the PRNG keys => every process builds identical
    # host-side state before placement (parallel/multihost.py docstring)
    clients = synthetic_clients(n_clients=n_real, dim=dim, n_normal=40,
                                n_abnormal=16)
    dev_x = build_dev_dataset(clients, rngs.data_rng)
    data = stack_clients(clients, dev_x, cfg.batch_size, pad_clients_to=8)

    mesh = client_mesh()  # all 8 global devices: spans both processes
    assert mesh.devices.size == 8
    model = make_model("hybrid", dim, shrink_lambda=cfg.shrink_lambda)
    engine = RoundEngine(model, cfg, data, n_real=n_real, rngs=rngs,
                         model_type="hybrid", update_type="mse_avg",
                         fused=True)
    engine.data, engine.states = shard_federation(data, engine.states, mesh)
    engine._ver_x, engine._ver_m = engine._verification_tensors()

    result = engine.run_round(0)
    metrics = np.asarray(result.client_metrics)
    assert metrics.shape == (n_real,), metrics.shape
    assert np.all(np.isfinite(metrics)), metrics
    assert result.aggregator is not None
    # the host control plane must agree across processes (same seeds, same
    # allgathered device results) — print for the parent to cross-check
    print(f"MULTIHOST_OK pid={pid} agg={result.aggregator} "
          f"mean={float(np.nanmean(metrics)):.6f}", flush=True)


if __name__ == "__main__":
    main()
