"""Redteam attack-success-vs-defense grids (ISSUE 17): the measurement
half of fedmse_tpu/redteam/ (DESIGN.md §21, ROADMAP item 5).

The PR 3 threat model (ATTACK_r05.json) stops at static update poisoning
of the single-global federation. This sweep attacks the three decision
surfaces grown since, each with an ADAPTIVE adversary that reads the
defender's state, and measures the paired defense's bite AND its clean
cost:

  * **cluster-assignment poisoning** — insiders inside a victim cluster
    scale-poison their updates (harm cell: honest co-members' AUC), and
    mimics FORGE their latent statistics toward the victim's pooled
    Gaussian to be captured into its merge (mimic_latent_stats, blend
    grid). Defense: assignment hysteresis (refit_with_hysteresis) — a
    gateway moves only when the alternative is decisively closer, so
    partial forgeries stall at the margin. The sweep records where the
    defense provably fails: blend=1.0 IS the victim's Gaussian, and no
    stats-based assignment can tell forged from genuine.
  * **flywheel slow-drift self-poisoning** — SlowDriftAdversary walks
    its traffic toward a target, step-by-step, keeping each batch just
    inside the verdict envelope; every threshold refit over the
    poisoned reservoir ratchets the envelope toward the adversary (the
    self-poisoning feedback loop). Defense: reservoir admission
    hardening (FlywheelBuffer margin_frac floor + influence_cap). The
    detector here is the analytic distance-to-calibrated-centroid
    scorer: the attack and defense live entirely in the ADMISSION
    POLICY (scores vs thresholds), so detector realism is orthogonal to
    what the cell measures.
  * **sybil churn** — a coalition rides elastic joins into the fleet
    and votes for its own candidates (lie_votes): election capture.
    Defense: the tenure gate (min_tenure defers recycled tenants'
    candidacy + votes). A paired probe measures the verification
    recovery-waiver abuse the PR 1 CAVEAT predicted — repeated
    large-delta broadcasts each accepted as "recovery" — against the
    cumulative recovery_budget ceiling (config.recovery_budget).

Clean-cost rows pin that the defenses are free when nobody attacks:
defenses-off is BITWISE identical to no-redteam (null-spec pin), and
each defense's clean AUC delta is bounded (<= 2e-3; the tenure gate's
residual cost is measured in deferred elections).

Writes REDTEAM.json (override with --out); one JSON line per row.
Run: `make redteam-sweep` (env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu
python redteam_sweep.py --out REDTEAM_r17.json). Hermetic CPU like the
tests.
"""

import json
import os
import sys
import time

REPO_ROOT = os.path.dirname(os.path.abspath(__file__))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

DIM = 16
ROUNDS = 6
CLEAN_AUC_EPS = 2e-3


def base_cfg(**kw):
    from fedmse_tpu.config import CompatConfig, ExperimentConfig
    base = dict(
        dim_features=DIM, hidden_neus=12, latent_dim=5, epochs=6,
        batch_size=16, num_rounds=ROUNDS, network_size=8,
        compat=CompatConfig(vote_tie_break=False))
    base.update(kw)
    return ExperimentConfig(**base)


def build_typed_grid(cfg, n_clients=8, types=2, seed=11):
    from fedmse_tpu.data import build_dev_dataset, stack_clients
    from fedmse_tpu.data.synthetic import synthetic_typed_clients
    from fedmse_tpu.utils.seeding import ExperimentRngs
    clients = synthetic_typed_clients(
        n_clients=n_clients, types=types, dim=cfg.dim_features,
        n_normal=200, n_abnormal=80, seed=seed)
    dev_x = build_dev_dataset(clients, ExperimentRngs(
        run=0, data_seed=cfg.data_seed).data_rng)
    return stack_clients(clients, dev_x, cfg.batch_size), len(clients)


def build_plain_grid(cfg, n_clients, seed=0):
    from fedmse_tpu.data import (build_dev_dataset, stack_clients,
                                 synthetic_clients)
    from fedmse_tpu.utils.seeding import ExperimentRngs
    del seed
    clients = synthetic_clients(n_clients=n_clients, dim=cfg.dim_features,
                                n_normal=160, n_abnormal=64)
    dev_x = build_dev_dataset(clients, ExperimentRngs(
        run=0, data_seed=cfg.data_seed).data_rng)
    return stack_clients(clients, dev_x, cfg.batch_size), len(clients)


def run_cell(cfg, data, n_real, spec=None, elastic=None, redteam=None,
             model_type="autoencoder", label="cell"):
    """One federation; returns (per-gateway final AUC, results, engine)."""
    import numpy as np
    from fedmse_tpu.federation import RoundEngine
    from fedmse_tpu.models import make_model
    from fedmse_tpu.parallel import host_fetch
    from fedmse_tpu.utils.seeding import ExperimentRngs

    model = make_model(model_type, cfg.dim_features, cfg.hidden_neus,
                       cfg.latent_dim, shrink_lambda=cfg.shrink_lambda)
    engine = RoundEngine(model, cfg, data, n_real=n_real,
                         rngs=ExperimentRngs(run=0, data_seed=cfg.data_seed),
                         model_type=model_type, update_type="mse_avg",
                         fused=True, cluster=spec, elastic=elastic,
                         redteam=redteam)
    results, _, _ = engine.run_schedule_chunk(0, cfg.num_rounds)
    final = np.asarray(host_fetch(engine.evaluate_all(
        engine.states.params, data.test_x, data.test_m, data.test_y,
        data.train_xb, data.train_mb)))[:n_real]
    return final, results, engine


# ------------------------------------------------- defenses-off pin ----

def defenses_off_pin():
    """RedteamSpec() (null) vs no spec at all: states bitwise after a
    short schedule — defenses off costs literally nothing."""
    import numpy as np
    import jax
    from fedmse_tpu.redteam import RedteamSpec

    cfg = base_cfg(num_rounds=3)
    data, n_real = build_plain_grid(cfg, 6)
    _, _, plain = run_cell(cfg, data, n_real, label="pin-plain")
    _, _, null = run_cell(cfg, data, n_real, redteam=RedteamSpec(),
                          label="pin-null")
    bit = all(np.array_equal(np.asarray(a), np.asarray(b))
              for a, b in zip(jax.tree.leaves(plain.states),
                              jax.tree.leaves(null.states)))
    return {"label": "defenses_off_bitwise_pin",
            "states_bit_identical": bool(bit)}


# ------------------------------------- A. cluster-assignment poisoning ----

def cluster_cells():
    import numpy as np
    from fedmse_tpu.cluster import ClusterSpec, refit_with_hysteresis
    from fedmse_tpu.redteam import (RedteamSpec, assignment_capture_rate,
                                    mimic_latent_stats)

    rows = []
    cfg = base_cfg()
    data, n_real = build_typed_grid(cfg)
    spec = ClusterSpec(k=2)

    # clean clustered baseline: the fit the mimics will forge against
    clean, _, eng = run_cell(cfg, data, n_real, spec=spec, label="clean-k2")
    fit = eng.cluster_fit
    assignment = fit.assignment
    victim = int(assignment[0])
    members = np.flatnonzero(assignment == victim)
    outsiders = np.flatnonzero(assignment != victim)

    # ---- insider poison harm: 2 victim-cluster insiders scale their
    # updates; success = honest co-members' AUC drop ----
    insiders = tuple(int(i) for i in members[:2])
    honest = np.setdiff1d(members, insiders)
    atk = RedteamSpec(kind="cluster_poison", adversaries=insiders,
                      victim_cluster=victim, poison="scale", strength=8.0)
    poisoned, _, _ = run_cell(cfg, data, n_real, spec=spec, redteam=atk,
                              label="insider-poison")
    harm = float(np.nanmean(clean[honest]) - np.nanmean(poisoned[honest]))
    rows.append({
        "label": "cluster/insider_poison_harm",
        "victim_cluster": victim, "insiders": list(insiders),
        "honest_members": honest.tolist(),
        "clean_auc_honest": round(float(np.nanmean(clean[honest])), 4),
        "poisoned_auc_honest": round(float(np.nanmean(poisoned[honest])), 4),
        "undefended_auc_drop": round(harm, 4),
        "outsider_auc_delta": round(
            float(np.nanmean(clean[outsiders])
                  - np.nanmean(poisoned[outsiders])), 4),
    })

    # ---- mimicry capture vs hysteresis: outsiders forge their latent
    # stats toward the victim's pooled Gaussian; the refit either takes
    # the bait (h=0, plain nearest-reference moves) or holds (h=0.5).
    # Below blend ~0.8 the forgers' own residue drags their OWN pooled
    # reference toward the victim (self-contamination keeps them home);
    # the capture window opens at ~0.8 — exactly where hysteresis holds
    # and plain refits flip ----
    adv_ids = tuple(int(i) for i in outsiders[:2])
    blend_rows = {}
    for blend in (0.7, 0.8, 1.0):
        fm, fc = mimic_latent_stats(fit.means, fit.covs, adv_ids,
                                    fit.cl_means[victim],
                                    fit.cl_covs[victim], blend)
        cell = {}
        for h in (0.0, 0.5):
            out = refit_with_hysteresis(fm, fc, assignment, spec.k, h)
            cell[h] = assignment_capture_rate(out.assignment, adv_ids,
                                              victim)
        blend_rows[blend] = cell
    undef = blend_rows[0.8][0.0]
    defended = blend_rows[0.8][0.5]
    rows.append({
        "label": "cluster/mimicry_capture",
        "adversaries": list(adv_ids), "victim_cluster": victim,
        "capture_by_blend": {
            str(b): {"undefended_h0": c[0.0], "hysteresis_h0.5": c[0.5]}
            for b, c in blend_rows.items()},
        "undefended_capture_at_0.8": undef,
        "defended_capture_at_0.8": defended,
        "provable_failure": "blend=1.0 equals the victim's pooled "
                            "Gaussian exactly; capture_by_blend['1.0'] "
                            "shows hysteresis cannot (and should not "
                            "claim to) separate a perfect forgery",
    })

    # ---- clean cost: hysteresis on a refitting clean run ----
    h_cfg = cfg
    c0, _, _ = run_cell(h_cfg, data, n_real,
                        spec=ClusterSpec(k=2, refit_every=2),
                        label="clean-h0")
    c1, _, _ = run_cell(h_cfg, data, n_real,
                        spec=ClusterSpec(k=2, refit_every=2,
                                         hysteresis=0.5),
                        label="clean-h0.5")
    clean_delta = float(abs(np.nanmean(c0) - np.nanmean(c1)))
    rows.append({
        "label": "cluster/hysteresis_clean_cost",
        "clean_auc_h0": round(float(np.nanmean(c0)), 4),
        "clean_auc_h0.5": round(float(np.nanmean(c1)), 4),
        "clean_auc_delta": round(clean_delta, 6),
    })
    return rows, {
        "undefended_capture": undef, "defended_capture": defended,
        "insider_auc_drop": harm, "clean_auc_delta": clean_delta,
    }


# --------------------------------------- B. flywheel slow-drift loop ----

def drift_loop(margin_frac, steps=60, refit_every=3, seed=3):
    """The closed self-poisoning loop: serve -> verdict -> admit ->
    threshold refit over the reservoir -> serve. The adversary observes
    only its own verdicts (normal_fraction); the defender's margin floor
    decides which of the verdicted-normal rows may enter the reservoir
    that the NEXT threshold is fitted from. Calibration is mean+3*std of
    the pool's scores (the extrapolating envelope a real refit uses —
    the statistic that makes self-poisoning POSSIBLE: near-threshold
    admissions widen the fitted spread, and the envelope walks),
    floored at the audited seed calibration: the envelope never SHRINKS
    on unaudited reservoir data (shrinkage is the verdict-inversion
    vector, and the floor also cancels the margin filter's truncation
    bias on the fitted std — measured in the clean-cost row)."""
    import numpy as np
    from fedmse_tpu.flywheel.buffer import FlywheelBuffer
    from fedmse_tpu.redteam import SlowDriftAdversary, normal_fraction

    rng = np.random.default_rng(seed)
    mu0 = np.zeros(DIM, np.float32)
    target = np.zeros(DIM, np.float32)
    target[0] = 8.0

    def score(x):
        return np.mean((x - mu0) ** 2, axis=1)

    def fit(pool_scores):
        return float(pool_scores.mean() + 3.0 * pool_scores.std())

    seed_rows = rng.normal(mu0, 1.0, (512, DIM)).astype(np.float32)
    thr = [fit(score(seed_rows))]
    thr0 = thr[0]

    buf = FlywheelBuffer(
        1, DIM, capacity=8192,
        margin_frac=margin_frac,
        thresholds_fn=(lambda: np.array([thr[0]]))
        if margin_frac is not None else None)
    adv = SlowDriftAdversary(mu0, target, seed=seed, step=0.05)
    admitted_adv = 0
    for t in range(steps):
        batch = adv.next_batch(64)
        sc = score(batch)
        verdicts = sc > thr[0]                       # True = anomalous
        adv.observe(normal_fraction(verdicts))
        admitted_adv += buf.admit(batch, np.zeros(len(batch), np.int64),
                                  verdicts=verdicts, scores=sc)
        if (t + 1) % refit_every == 0:
            # refit over calibration seed + everything the reservoir
            # admitted — the flywheel's refit_calibration dataflow
            pool = np.concatenate([seed_rows, buf.rows_for(0)])
            thr[0] = max(fit(score(pool)), thr0)
    return {
        "final_position": round(adv.position, 4),
        "threshold_inflation": round(thr[0] / thr0, 4),
        "admitted_adversarial_rows": int(admitted_adv),
        "reservoir_rows": int(buf.count[0]),
    }


def flywheel_cells():
    import numpy as np
    from fedmse_tpu.flywheel.buffer import FlywheelBuffer

    rows = []
    undef = drift_loop(margin_frac=None)
    defended = drift_loop(margin_frac=0.7)
    rows.append({
        "label": "flywheel/slow_drift_self_poisoning",
        "undefended": undef, "margin_frac_0.7": defended,
        "note": "undefended, every near-threshold batch the verdicts "
                "pass enters the refit pool and ratchets the envelope "
                "until the adversary reaches its target; the margin "
                "floor admits only rows well inside the envelope, so "
                "the refit pool cannot walk and the adversary stalls at "
                "the FIXED envelope's operating point",
    })

    # ---- influence cap: a flooding gateway's share of finetune rows ----
    lens = {}
    for cap in (None, 0.34):
        rng = np.random.default_rng(0)
        buf = FlywheelBuffer(3, DIM, capacity=1024, influence_cap=cap)
        buf.admit(rng.normal(size=(400, DIM)), np.full(400, 0))
        buf.admit(rng.normal(size=(60, DIM)), np.full(60, 1))
        buf.admit(rng.normal(size=(60, DIM)), np.full(60, 2))
        ft = buf.build_finetune_data(
            16, dev_x=np.zeros((8, DIM), np.float32), min_rows=8)
        n = [len(r) for r in ft.train_rows]
        lens[cap] = {"rows_per_gateway": n,
                     "flooder_share": round(n[0] / max(1, sum(n)), 4)}
    rows.append({
        "label": "flywheel/influence_cap",
        "uncapped": lens[None], "cap_0.34": lens[0.34],
    })

    # ---- clean cost: drift-free traffic, margin on vs off; detector
    # verdict accuracy on held-out normals vs fixed anomalies after the
    # loop (same mean+3*std calibration as the attack cell) ----
    rng = np.random.default_rng(9)
    mu0 = np.zeros(DIM, np.float32)

    def fit(pool_scores):
        return float(pool_scores.mean() + 3.0 * pool_scores.std())

    def clean_loop(margin):
        seed_rows = rng.normal(mu0, 1.0, (512, DIM)).astype(np.float32)
        thr0 = fit(np.mean(seed_rows ** 2, axis=1))
        thr = [thr0]
        buf = FlywheelBuffer(
            1, DIM, capacity=4096, margin_frac=margin,
            thresholds_fn=(lambda: np.array([thr[0]]))
            if margin is not None else None)
        streamed = admitted = 0
        for t in range(20):
            batch = rng.normal(mu0, 1.0, (64, DIM)).astype(np.float32)
            sc = np.mean(batch ** 2, axis=1)
            verd = sc > thr[0]
            streamed += int((~verd).sum())
            admitted += buf.admit(batch, np.zeros(64, np.int64),
                                  verdicts=verd, scores=sc)
            if (t + 1) % 5 == 0:
                pool = np.concatenate([seed_rows, buf.rows_for(0)])
                thr[0] = max(fit(np.mean(pool ** 2, axis=1)), thr0)
        return thr[0], admitted / max(1, streamed)

    eval_rng = np.random.default_rng(123)
    normals = eval_rng.normal(mu0, 1.0, (512, DIM)).astype(np.float32)
    anoms = (eval_rng.normal(mu0, 1.0, (512, DIM)).astype(np.float32)
             + 1.2)

    def auc_at(thr):
        # threshold-free ranking AUC is margin-invariant here (the
        # scorer is fixed); the defense can only shift the THRESHOLD, so
        # the clean-cost AUC axis is the verdict accuracy at thr
        sn = np.mean(normals ** 2, axis=1) > thr
        sa = np.mean(anoms ** 2, axis=1) > thr
        return 0.5 * ((~sn).mean() + sa.mean())

    thr_off, ret_off = clean_loop(None)
    thr_on, ret_on = clean_loop(0.7)
    clean_delta = float(abs(auc_at(thr_on) - auc_at(thr_off)))
    rows.append({
        "label": "flywheel/margin_clean_cost",
        "threshold_margin_off": round(thr_off, 4),
        "threshold_margin_on": round(thr_on, 4),
        "clean_admission_retention": round(ret_on / max(ret_off, 1e-9), 4),
        "clean_verdict_auc_delta": round(clean_delta, 6),
    })
    return rows, {
        "undefended_position": undef["final_position"],
        "defended_position": defended["final_position"],
        "undefended_inflation": undef["threshold_inflation"],
        "defended_inflation": defended["threshold_inflation"],
        "flooder_share_uncapped": lens[None]["flooder_share"],
        "flooder_share_capped": lens[0.34]["flooder_share"],
        "clean_auc_delta": clean_delta,
    }


# ------------------------------------------------- C. sybil churn ----

def sybil_cells():
    import numpy as np
    from fedmse_tpu.federation.elastic import ElasticSpec
    from fedmse_tpu.redteam import RedteamSpec

    rows = []
    cfg = base_cfg(network_size=12, num_rounds=16)
    data, n_real = build_plain_grid(cfg, 12)
    # the join blitz: half the fleet are founders, the other half's
    # slots open at round 8 and fill fast — the coalition rides the
    # wave in and immediately bids for the coordinator role
    elastic = ElasticSpec(leave_p=0.0, join_p=0.9,
                          initial_member_frac=0.5,
                          join_window=(8, None))

    # scout the (redteam-independent) elastic timeline: the coalition
    # is exactly the slots the wave recycles
    clean, clean_res, scout = run_cell(cfg, data, n_real, elastic=elastic,
                                       label="sybil-scout")
    scout._elastic_masks(0, cfg.num_rounds)
    gen = np.asarray(scout._elastic_premade.generation)[:, :n_real]
    recycled = np.flatnonzero(gen.max(axis=0) > 0)
    adv_ids = tuple(int(i) for i in recycled)

    blitz_start = 8

    def capture(results, start=0):
        agg_rounds = [r.aggregator for r in results[start:]
                      if r.aggregator is not None]
        if not agg_rounds:
            return 0.0, 0
        hits = sum(1 for a in agg_rounds if a in adv_ids)
        return hits / len(agg_rounds), len(agg_rounds)

    cells = {}
    for name, spec in (
            ("undefended", RedteamSpec(kind="sybil", adversaries=adv_ids,
                                       lie_votes=True)),
            ("min_tenure_6", RedteamSpec(kind="sybil", adversaries=adv_ids,
                                         lie_votes=True, min_tenure=6))):
        auc, results, _ = run_cell(cfg, data, n_real, elastic=elastic,
                                   redteam=spec, label=f"sybil-{name}")
        rate, n_agg = capture(results)
        wrate, wn = capture(results, blitz_start)
        cells[name] = {"capture_rate": round(rate, 4),
                       "capture_rate_post_blitz": round(wrate, 4),
                       "aggregated_rounds": n_agg,
                       "aggregated_rounds_post_blitz": wn,
                       "auc_mean": round(float(np.nanmean(auc)), 4)}
    base_rate, _ = capture(clean_res)
    base_wrate, _ = capture(clean_res, blitz_start)
    rows.append({
        "label": "sybil/election_capture",
        "adversaries": list(adv_ids),
        "recycled_slots": recycled.tolist(),
        "blitz_start_round": blitz_start,
        "honest_baseline_capture": round(base_rate, 4),
        "honest_baseline_capture_post_blitz": round(base_wrate, 4),
        **cells,
        "note": "before the blitz no coalition slot is even a member — "
                "the post-blitz rates are the attack's operating window",
    })

    # ---- clean cost: the tenure gate on an HONEST churning fleet ----
    defonly, defres, _ = run_cell(
        cfg, data, n_real, elastic=elastic,
        redteam=RedteamSpec(min_tenure=6), label="sybil-defonly")
    deferred = sum(
        1 for a, b in zip(clean_res, defres)
        if (a.aggregator is None) != (b.aggregator is None)
        or (a.aggregator is not None and a.aggregator != b.aggregator))
    clean_delta = float(abs(np.nanmean(clean) - np.nanmean(defonly)))
    rows.append({
        "label": "sybil/tenure_gate_clean_cost",
        "clean_auc": round(float(np.nanmean(clean)), 4),
        "defense_only_auc": round(float(np.nanmean(defonly)), 4),
        "clean_auc_delta": round(clean_delta, 6),
        "elections_changed": deferred,
        "note": "the gate defers recycled tenants' candidacy+votes even "
                "when honest; its residual cost is the elections it "
                "re-routes, bounded by the join rate",
    })
    return rows, {
        "undefended_capture": cells["undefended"]["capture_rate_post_blitz"],
        "defended_capture": cells["min_tenure_6"]["capture_rate_post_blitz"],
        "honest_baseline": base_wrate,
        "clean_auc_delta": clean_delta,
    }


# --------------------------------- verification recovery-waiver abuse ----

def waiver_abuse_cell():
    """The PR 1 CAVEAT weaponized: an adversary controlling broadcasts
    ships a SEQUENCE of large-delta models, each individually passing
    the recovery waiver — undefended, the cumulative accepted Frobenius
    influence grows linearly; recovery_budget caps it."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax
    from fedmse_tpu.federation.state import init_client_states
    from fedmse_tpu.federation.verification import make_verify_fn
    from fedmse_tpu.models import make_model

    n, probes = 4, 6
    model = make_model("hybrid", DIM)
    states0 = init_client_states(model, optax.adam(1e-3),
                                 jax.random.key(0), n)
    states0 = type(states0)(
        params=states0.params, opt_state=states0.opt_state,
        prev_global=states0.prev_global, hist_params=states0.hist_params,
        hist_perf=states0.hist_perf, hist_seen=jnp.ones((n,), bool),
        rejected=states0.rejected, waived=states0.waived)
    common = dict(verification_threshold=1e-6, performance_threshold=10.0,
                  hardened=True, recovery_threshold=-1.0,
                  recovery_delta_cap=1e9)
    ver_x = jnp.zeros((n, 6, DIM))
    ver_m = jnp.ones((n, 6))
    aggs = [jax.tree.map(lambda t, r=r: t[0] + 0.5 * (r + 1),
                         states0.params) for r in range(probes)]

    def run(budget):
        verify = make_verify_fn(model, recovery_budget=budget, **common)
        states, accepted = states0, 0
        for agg in aggs:
            out = verify(states, agg, ver_x, ver_m, jnp.zeros((n,)),
                         jnp.ones((n,)))
            accepted += int(np.asarray(out.accepted).sum())
            states = out.states
        return accepted, float(np.asarray(states.waived).max())

    acc_off, waived_off = run(None)
    budget = waived_off / probes * 1.5          # ~1.5 probes' worth
    acc_on, waived_on = run(budget)
    return {
        "label": "verification/recovery_waiver_abuse",
        "probes": probes,
        "undefended": {"accepted": acc_off,
                       "cumulative_waived_frobenius": round(waived_off, 4)},
        "recovery_budget": round(budget, 4),
        "defended": {"accepted": acc_on,
                     "cumulative_waived_frobenius": round(waived_on, 4)},
    }, {
        "undefended_waived": waived_off,
        "defended_waived": waived_on,
        "budget": budget,
    }


def quick_cell():
    """Reduced redteam guard for bench_suite scenario 19: the
    defenses-off bitwise pin, one mimicry capture point (blend 0.8,
    plain refit vs hysteresis 0.5) and the reservoir margin-floor
    admission bound. The committed standalone artifact
    (make redteam-sweep -> REDTEAM_r17.json) carries the full blend
    grids, the drift loop, sybil blitz and waiver-abuse cells."""
    import numpy as np
    from fedmse_tpu.cluster import ClusterSpec, refit_with_hysteresis
    from fedmse_tpu.flywheel.buffer import FlywheelBuffer
    from fedmse_tpu.redteam import assignment_capture_rate, mimic_latent_stats

    pin = defenses_off_pin()["states_bit_identical"]

    cfg = base_cfg()
    data, n_real = build_typed_grid(cfg)
    spec = ClusterSpec(k=2)
    _, _, eng = run_cell(cfg, data, n_real, spec=spec, label="quick-clean")
    fit = eng.cluster_fit
    victim = int(fit.assignment[0])
    adv_ids = tuple(int(i)
                    for i in np.flatnonzero(fit.assignment != victim)[:2])
    fm, fc = mimic_latent_stats(fit.means, fit.covs, adv_ids,
                                fit.cl_means[victim], fit.cl_covs[victim],
                                0.8)
    undef = assignment_capture_rate(
        refit_with_hysteresis(fm, fc, fit.assignment, spec.k,
                              0.0).assignment, adv_ids, victim)
    defended = assignment_capture_rate(
        refit_with_hysteresis(fm, fc, fit.assignment, spec.k,
                              0.5).assignment, adv_ids, victim)

    # margin floor: of four near-threshold verdicted-normal rows, only
    # the ones below thr * (1 - margin) may enter the refit reservoir
    thr = np.array([1.0], np.float32)
    buf = FlywheelBuffer(1, DIM, capacity=64, margin_frac=0.5,
                         thresholds_fn=lambda: thr)
    sc = np.array([0.2, 0.9, 0.4, 0.51], np.float32)
    admitted = buf.admit(np.zeros((4, DIM), np.float32),
                         np.zeros(4, np.int64),
                         verdicts=np.zeros(4, bool), scores=sc)

    ok = bool(pin and undef >= 0.5 and defended <= 0.5 * undef
              and admitted == 2)
    return {"defenses_off_bitwise": bool(pin),
            "mimicry_blend_0.8": {"undefended_capture": undef,
                                  "hysteresis_0.5_capture": defended},
            "margin_floor_admitted": {"scores": sc.tolist(),
                                      "threshold": 1.0, "margin_frac": 0.5,
                                      "admitted": int(admitted)},
            "acceptance_met": ok}


def main():
    from fedmse_tpu.utils.platform import (capture_provenance,
                                           enable_compilation_cache)
    enable_compilation_cache()
    capture_provenance()
    import jax

    t0 = time.time()
    rows = []

    def emit(row):
        rows.append(row)
        print(json.dumps(row), flush=True)
        return row

    pin = emit(defenses_off_pin())
    cl_rows, cl = cluster_cells()
    for r in cl_rows:
        emit(r)
    fw_rows, fw = flywheel_cells()
    for r in fw_rows:
        emit(r)
    sy_rows, sy = sybil_cells()
    for r in sy_rows:
        emit(r)
    wv_row, wv = waiver_abuse_cell()
    emit(wv_row)
    from fedmse_tpu.redteam import cost_gaming_cell, shed_storm_cell
    st_rows, st = shed_storm_cell()
    for r in st_rows:
        emit({"cell": "shed_storm", **r})
    cg_rows, cg = cost_gaming_cell()
    for r in cg_rows:
        emit({"cell": "cost_gaming", **r})

    def factor(a, b, floor=1e-9):
        return round(a / max(b, floor), 2)

    acceptance = {
        "bar": "each adversary's undefended success quantified; the "
               "paired defense cuts it by the stated factor; clean AUC "
               "deltas <= 2e-3; defenses-off bitwise-identical to "
               "no-redteam",
        "defenses_off_bitwise": pin["states_bit_identical"],
        "cluster": {
            "undefended_capture": cl["undefended_capture"],
            "defended_capture": cl["defended_capture"],
            "defense_factor": factor(cl["undefended_capture"],
                                     cl["defended_capture"]),
            "insider_auc_drop": round(cl["insider_auc_drop"], 4),
            "clean_auc_delta": round(cl["clean_auc_delta"], 6),
            "met": bool(cl["undefended_capture"] >= 0.5
                        and cl["defended_capture"]
                        <= 0.5 * cl["undefended_capture"]
                        and cl["clean_auc_delta"] <= CLEAN_AUC_EPS),
        },
        "flywheel": {
            "undefended_position": fw["undefended_position"],
            "defended_position": fw["defended_position"],
            "defense_factor": factor(fw["undefended_position"],
                                     fw["defended_position"]),
            "threshold_inflation": {
                "undefended": fw["undefended_inflation"],
                "defended": fw["defended_inflation"]},
            "flooder_share": {
                "uncapped": fw["flooder_share_uncapped"],
                "capped": fw["flooder_share_capped"]},
            "clean_auc_delta": round(fw["clean_auc_delta"], 6),
            # the success axis is the SELF-POISONING itself — how far the
            # envelope walked (inflation - 1); the defended stall
            # position is the fixed envelope's intrinsic operating
            # point, not a defense failure
            "met": bool(fw["undefended_inflation"] >= 1.5
                        and abs(fw["defended_inflation"] - 1.0)
                        <= 0.2 * (fw["undefended_inflation"] - 1.0)
                        and fw["defended_position"]
                        < fw["undefended_position"]
                        and fw["flooder_share_capped"]
                        < fw["flooder_share_uncapped"]
                        and fw["clean_auc_delta"] <= CLEAN_AUC_EPS),
        },
        "sybil": {
            "undefended_capture": sy["undefended_capture"],
            "defended_capture": sy["defended_capture"],
            "honest_baseline": sy["honest_baseline"],
            "defense_factor": factor(sy["undefended_capture"],
                                     sy["defended_capture"]),
            "clean_auc_delta": round(sy["clean_auc_delta"], 6),
            "met": bool(sy["undefended_capture"] > sy["honest_baseline"]
                        and sy["defended_capture"]
                        <= 0.5 * sy["undefended_capture"]
                        and sy["clean_auc_delta"] <= CLEAN_AUC_EPS),
        },
        "waiver": {
            "undefended_waived": round(wv["undefended_waived"], 4),
            "defended_waived": round(wv["defended_waived"], 4),
            "budget": round(wv["budget"], 4),
            "met": bool(wv["defended_waived"]
                        <= 0.5 * wv["undefended_waived"]),
        },
        # the ingest plane (gateway/): authenticated-coalition attacks
        # on the two post-handshake decisions (redteam/ingest.py)
        "shed_storm": {
            "undefended_honest_shed_frac":
                round(st["undefended_honest_shed_frac"], 4),
            "defended_honest_shed_frac":
                round(st["defended_honest_shed_frac"], 4),
            "defense_factor": factor(st["undefended_honest_shed_frac"],
                                     st["defended_honest_shed_frac"]),
            "clean_cost_shed_frac": round(st["clean_cost_shed_frac"], 6),
            "met": bool(st["undefended_honest_shed_frac"] >= 0.5
                        and st["defended_honest_shed_frac"]
                        <= 0.1 * st["undefended_honest_shed_frac"]
                        and st["clean_cost_shed_frac"] <= 1e-6
                        and st["clean_rows_isolated"] == 0),
        },
        "cost_gaming": {
            "undefended_shed_rows": round(cg["undefended_shed_rows"], 1),
            "defended_shed_rows": round(cg["defended_shed_rows"], 1),
            "shed_defense_factor": factor(cg["undefended_shed_rows"],
                                          cg["defended_shed_rows"]),
            "scale_flaps": {"undefended": cg["undefended_scale_flaps"],
                            "defended": cg["defended_scale_flaps"]},
            "flap_defense_factor": factor(cg["undefended_scale_flaps"],
                                          cg["defended_scale_flaps"]),
            "clean_extra_usd": cg["clean_extra_usd"],
            "met": bool(cg["defended_shed_rows"]
                        <= 0.5 * cg["undefended_shed_rows"]
                        and cg["defended_scale_flaps"]
                        <= 0.5 * cg["undefended_scale_flaps"]
                        and cg["clean_overload_ticks_defended"] == 0),
        },
    }
    acceptance["met"] = bool(
        acceptance["defenses_off_bitwise"]
        and acceptance["cluster"]["met"] and acceptance["flywheel"]["met"]
        and acceptance["sybil"]["met"] and acceptance["waiver"]["met"]
        and acceptance["shed_storm"]["met"]
        and acceptance["cost_gaming"]["met"])

    device = jax.devices()[0]
    out = {
        "metric": "attack success rate vs measured defense across the "
                  "cluster / flywheel / elastic decision surfaces "
                  "(DESIGN.md §21)",
        "rows": rows,
        "acceptance": acceptance,
        "total_seconds": round(time.time() - t0, 1),
        "device": str(device), "platform": device.platform,
        **capture_provenance(),
    }
    dest = "REDTEAM.json"
    for i, a in enumerate(sys.argv):
        if a == "--out" and i + 1 < len(sys.argv):
            dest = sys.argv[i + 1]
        elif a.startswith("--out="):
            dest = a.split("=", 1)[1]
    with open(dest, "w") as f:
        f.write(json.dumps(out) + "\n")
    print(json.dumps({"wrote": dest, "acceptance_met": acceptance["met"]}))


if __name__ == "__main__":
    main()
