#!/bin/bash
# Second-wave single-shot watcher (round 4): the first battery landed the
# full artifact set but the tunnel wedged before the 200-client point and
# the chunk-32 paper capture. When the tunnel recovers, serially capture:
#   1. quick-run bench (chunk-32 engine; a quieter window than the 0.0663
#      battery capture would also improve the headline row)
#   2. paper-scale with the shipped chunk-32 default
#   3. the first 200-client on-chip point
# Launch detached: setsid nohup bash watch_tpu_r04b.sh & — exits after one
# battery so it cannot collide with the driver's end-of-round bench.
set -u
cd "$(dirname "$0")"
OUT=${1:-/tmp/tpu_capture_r04b}
LOG=${OUT}.watch.log
DEADLINE=$(( $(date +%s) + ${2:-21600} ))  # default 6 h, then give up —
# the watcher must be long gone before the driver's end-of-round bench
# touches the device (round 3 lost its TPU capture to exactly that race).
# The deadline bounds the WHOLE run, so stop probing while a worst-case
# battery (3 steps x 1500 s timeouts + slack = 4800 s) still fits.
BATTERY_BUDGET=4800
mkdir -p "$OUT"
echo "watcher-b start $(date +%F\ %T)" >> "$LOG"
while true; do
    if [ "$(( $(date +%s) + BATTERY_BUDGET ))" -ge "$DEADLINE" ]; then
        echo "deadline headroom exhausted $(date +%F\ %T); giving up" >> "$LOG"
        exit 0
    fi
    if timeout 120 python -c "import jax; d=jax.devices()[0]; \
assert d.platform=='tpu', d.platform" >> "$LOG" 2>&1; then
        echo "tunnel healthy $(date +%F\ %T); capturing" >> "$LOG"
        for step in "bench_quick:python bench.py" \
                    "bench_paper32:python bench.py --paper-scale" \
                    "bench_c200:python bench.py --clients 200"; do
            name=${step%%:*}; cmd=${step#*:}
            echo "=== $name ($(date +%H:%M:%S))" >> "$LOG"
            timeout 1500 $cmd >"$OUT/$name.out" 2>"$OUT/$name.err" \
                || echo "--- $name FAILED rc=$?" >> "$LOG"
        done
        break
    fi
    echo "probe failed $(date +%F\ %T); sleeping 300s" >> "$LOG"
    sleep 300
done
# land only real TPU captures; commit nothing (the session reviews + lands)
for f in bench_quick bench_paper32 bench_c200; do
    [ -s "$OUT/$f.out" ] && grep -q '"platform": "tpu"' "$OUT/$f.out" \
        && echo "landed-candidate $f" >> "$LOG"
done
echo "watcher-b done $(date +%F\ %T)" >> "$LOG"
